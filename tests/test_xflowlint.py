"""xflowlint (xflow_tpu/analysis, tools/xflowlint.py,
tools/smoke_lint.sh): the fixture corpus proves every rule fires on
known-bad code — including the resurrected pre-PR 8 unlocked-appender
bug — and stays silent on the fixed shapes; suppression, baseline, and
CLI exit-code semantics are pinned; seeding a violation of each rule
class into a scratch copy of a REAL module is caught with the correct
rule id and file:line (the ISSUE 10 acceptance drill)."""

import json
import os
import re
import shutil
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from xflow_tpu.analysis.core import (  # noqa: E402
    Baseline, BaselineEntry, Finding, Module, Project, run_passes,
)

FIXTURES = os.path.join(REPO_ROOT, "tests", "fixtures", "xflowlint")


def lint(*paths, root=REPO_ROOT, rules=None):
    project = Project.load(root, [os.path.join(FIXTURES, p) if not
                                  os.path.isabs(p) else p for p in paths])
    only = set(rules) if rules else None
    return run_passes(project, only_rules=only)


def rules_of(findings):
    return sorted({f.rule for f in findings})


def marker_lines(fixture, rule):
    """Lines in a fixture carrying a `# XFnnn:` expectation marker."""
    out = set()
    with open(os.path.join(FIXTURES, fixture)) as f:
        for i, line in enumerate(f, 1):
            if f"# {rule}:" in line:
                out.add(i)
    return out


# ------------------------------------------------------------ rule firing


def test_jit_purity_fixture_fires_on_every_marker():
    findings = lint("bad_jit_purity.py")
    assert rules_of(findings) == ["XF101"]
    assert {f.line for f in findings} == marker_lines(
        "bad_jit_purity.py", "XF101")
    # the PR 2 rule by name: perf_counter inside a jit body
    assert any("time.perf_counter" in f.message for f in findings)
    # RNG, print, global, scan-body, and traced-lambda variants all land
    blob = " ".join(f.message for f in findings)
    for needle in ("random.random", "print", "global mutation",
                   "numpy.random.seed", "time.time"):
        assert needle in blob, needle


def test_recompile_fixture_fires_all_three_rules():
    findings = lint("bad_recompile.py")
    by_rule = {r: [f for f in findings if f.rule == r]
               for r in rules_of(findings)}
    assert set(by_rule) == {"XF201", "XF202", "XF203"}
    assert {f.line for f in by_rule["XF201"]} == marker_lines(
        "bad_recompile.py", "XF201")
    assert {f.line for f in by_rule["XF202"]} == marker_lines(
        "bad_recompile.py", "XF202")
    assert {f.line for f in by_rule["XF203"]} == marker_lines(
        "bad_recompile.py", "XF203")


def test_lockset_fixture_retro_detects_pre_pr8_appender():
    """The resurrected pre-PR 8 JsonlAppender (no append lock, health
    thread + handler threads) must fire on every unlocked mutation of
    the shared file-handle state."""
    findings = lint("bad_lockset.py")
    assert rules_of(findings) == ["XF301"]
    attrs = {re.search(r"`self\.(\w+)`", f.message).group(1)
             for f in findings}
    # the lazy-open handle and its byte counter are the bug
    assert "_f" in attrs and "_size" in attrs
    # every finding names both regions that collide
    for f in findings:
        assert "thread:_health_loop" in f.message
        assert "external" in f.message


def test_lockset_silent_on_fixed_appender():
    assert lint("good_lockset.py") == []


def test_config_fixture_fires_on_every_marker():
    findings = lint("bad_config.py")
    assert rules_of(findings) == ["XF401"]
    assert {f.line for f in findings} == marker_lines(
        "bad_config.py", "XF401")
    blob = " ".join(f.message for f in findings)
    for needle in ("train.lag_every", "sreve", "windw_ms", "train.epocs",
                   "serve.max_bach"):
        assert needle in blob, needle


def test_schema_fixture_fires_drift_and_unknown_kind():
    findings = lint("bad_schema.py")
    assert rules_of(findings) == ["XF501", "XF502"]
    msgs = " ".join(f.message for f in findings)
    assert "queue_wait_p50ms" in msgs  # drifted serve window key
    assert "stepp" in msgs  # drift against a stamp-declared kind
    assert '"shadow"' in msgs  # unknown kind


def test_shell_fixture_fires_strict_mode_and_bad_key():
    findings = lint("bad_shell.sh")
    assert rules_of(findings) == ["XF401", "XF601"]
    (f601,) = [f for f in findings if f.rule == "XF601"]
    assert "-o pipefail" in f601.message
    (f401,) = [f for f in findings if f.rule == "XF401"]
    assert "train.log_evry" in f401.message


def test_unrecorded_jit_fires_only_in_recorder_scoped_paths(tmp_path):
    """XF204 is scoped to the engine/serve modules where PR 7's
    CompileRecorder contract holds."""
    src = (
        "import jax\n"
        "def build(model):\n"
        "    def step(s, b):\n"
        "        return s\n"
        "    return jax.jit(step)\n"
    )
    scoped = tmp_path / "xflow_tpu" / "serve"
    scoped.mkdir(parents=True)
    (scoped / "newmod.py").write_text(src)
    unscoped = tmp_path / "xflow_tpu" / "data"
    unscoped.mkdir(parents=True)
    (unscoped / "newmod.py").write_text(src)
    findings = lint(str(scoped / "newmod.py"),
                    str(unscoped / "newmod.py"), root=str(tmp_path))
    assert rules_of(findings) == ["XF204"]
    assert [f.path for f in findings] == ["xflow_tpu/serve/newmod.py"]
    assert findings[0].line == 5


# ------------------------------------------------- precision (no false fire)


def test_loop_var_static_check_is_scope_local(tmp_path):
    """A parameter named like an unrelated loop variable in another
    function is NOT a loop variable (XF202 stays quiet)."""
    mod = tmp_path / "m.py"
    mod.write_text(
        "import jax\n\n\ndef f(x, n):\n    return x * n\n\n\n"
        "def other(xs):\n    for k in xs:\n        print(k)\n\n\n"
        "g = jax.jit(f, static_argnums=(1,))\n\n\n"
        "def call(k):\n    return g(1.0, k)\n"
    )
    assert lint(str(mod), rules=["XF202"]) == []


def test_lockset_private_thread_only_helper_not_external(tmp_path):
    """A private helper only the spawned thread calls is single-
    threaded — no finding; the same helper called from a PUBLIC method
    still fires."""
    base = (
        "import threading\n\n\nclass W:\n"
        "    def __init__(self):\n"
        "        self._buf = []\n"
        "        threading.Thread(target=self._run, daemon=True).start()\n\n"
        "    def _run(self):\n        self._flush()\n\n"
        "    def _flush(self):\n        self._buf = []\n"
    )
    mod = tmp_path / "w.py"
    mod.write_text(base)
    assert lint(str(mod), rules=["XF301"]) == []
    mod.write_text(base + "\n    def drain(self):\n        self._flush()\n")
    assert [f.rule for f in lint(str(mod), rules=["XF301"])] == ["XF301"]


def test_shell_strict_mode_must_precede_commands(tmp_path):
    """`set -euo pipefail` AFTER fallible commands protects nothing."""
    sh = tmp_path / "late.sh"
    sh.write_text("#!/usr/bin/env bash\nrm -rf \"$1\"\nset -euo pipefail\n")
    assert [f.rule for f in lint(str(sh))] == ["XF601"]


def test_shell_comment_mentions_of_keys_ignored(tmp_path):
    sh = tmp_path / "c.sh"
    sh.write_text("#!/usr/bin/env bash\nset -euo pipefail\n"
                  "# historical note: serve.windw_ms=3 was renamed\n"
                  "true\n")
    assert lint(str(sh)) == []


# ------------------------------------------------- suppression / negatives


def test_inline_and_file_suppressions():
    assert lint("suppress_line.py") == []
    assert lint("suppress_file.py") == []
    # the same code without the directive DOES fire (the suppression is
    # what silences it, not a pass gap)
    mod = Module("x.py", "x.py",
                 open(os.path.join(FIXTURES, "suppress_line.py")).read()
                 .replace("# xflowlint: disable=XF101", ""))
    assert not mod.line_suppress


def test_clean_fixture_is_clean():
    assert lint("good_clean.py") == []


# -------------------------------------------------------- baseline model


def _finding(rule="XF101", path="a.py", line=3, message="m"):
    return Finding(rule=rule, path=path, line=line, message=message)


def test_baseline_split_new_known_stale():
    base = Baseline([BaselineEntry("XF101", "a.py", "m", reason="legacy")])
    new, known, stale = base.split([_finding(), _finding(line=9)])
    # line numbers are NOT part of the fingerprint: both match
    assert not new and len(known) == 2 and not stale
    new, known, stale = base.split([_finding(message="other")])
    assert len(new) == 1 and not known and len(stale) == 1


def test_baseline_staleness_scoped_to_selected_rules():
    """`--rules XF301` skips the config pass — an XF401 baseline entry
    must not read as stale just because its pass never ran."""
    base = Baseline([BaselineEntry("XF401", "a.py", "m", reason="legacy")])
    _new, _known, stale = base.split([], only_rules={"XF301"})
    assert stale == []
    _new, _known, stale = base.split([], only_rules={"XF401"})
    assert len(stale) == 1
    _new, _known, stale = base.split([])  # full run: stale for real
    assert len(stale) == 1


def test_syntax_error_respects_rules_filter_and_suppression(tmp_path):
    bad = tmp_path / "broken.py"
    bad.write_text("def f(:\n")
    findings = lint(str(bad))
    assert rules_of(findings) == ["XF001"]
    # --rules excluding XF001 filters it
    assert lint(str(bad), rules=["XF301"]) == []
    # disable-file works even though the file never parsed
    bad.write_text("# xflowlint: disable-file=XF001 — generated junk\n"
                   "def f(:\n")
    assert lint(str(bad)) == []


def test_shell_all_wildcard_suppression(tmp_path):
    from xflow_tpu.analysis.core import ShellScript

    sh = ShellScript("x.sh", "x.sh",
                     "# xflowlint: disable-file=all\necho hi\n")
    assert sh.suppressed("XF601", 2)  # Module and ShellScript agree


def test_write_baseline_refuses_partial_scan_and_keeps_reasons(tmp_path):
    bad = os.path.join(FIXTURES, "bad_jit_purity.py")
    # partial path set + no explicit --baseline: refuse (3), never
    # clobber the repo-wide baseline with a partial scan
    r = run_cli(bad, "--write-baseline")
    assert r.returncode == 3 and "PARTIAL" in r.stderr
    # an audited reason survives regeneration of the same target
    bl = str(tmp_path / "bl.json")
    assert run_cli(bad, "--write-baseline", "--baseline", bl).returncode == 0
    base = Baseline.load(bl)
    assert base.entries
    base.entries[0].reason = "audited: fixture keeps this on purpose"
    base.save(bl)
    assert run_cli(bad, "--write-baseline", "--baseline", bl).returncode == 0
    kept = Baseline.load(bl)
    assert any(e.reason == "audited: fixture keeps this on purpose"
               for e in kept.entries)


def test_write_baseline_refuses_rule_scoped_scan():
    """--rules + --write-baseline would drop every other rule's audited
    entries — refused like the partial-path case."""
    r = run_cli("--rules", "XF301", "--write-baseline")
    assert r.returncode == 3 and "--rules" in r.stderr


def test_unrecorded_jit_catches_decorator_form(tmp_path):
    """`@jax.jit` (and `@partial(jax.jit, ...)`) in a recorder-scoped
    module bypasses compile accounting exactly like the call form."""
    scoped = tmp_path / "xflow_tpu" / "serve"
    scoped.mkdir(parents=True)
    (scoped / "m.py").write_text(
        "import jax\nfrom functools import partial\n\n\n"
        "@jax.jit\ndef step(s):\n    return s\n\n\n"
        "@partial(jax.jit, donate_argnums=(0,))\ndef step2(s):\n"
        "    return s\n"
    )
    findings = lint(str(scoped / "m.py"), root=str(tmp_path))
    assert [f.rule for f in findings] == ["XF204", "XF204"]
    # lineno of a decorated FunctionDef is the `def` line
    assert {f.line for f in findings} == {6, 11}


def test_schema_doc_parser_ignores_fenced_blocks(tmp_path):
    from xflow_tpu.analysis.passes.schema_drift import parse_schema_doc

    doc = tmp_path / "d.md"
    doc.write_text(
        '## Records (`kind="thing"`)\n\n'
        "```bash\n"
        "# this comment must not read as a heading\n"
        "| `not_a_key` | fenced tables are examples |\n"
        "```\n\n"
        "| field | meaning |\n"
        "|---|---|\n"
        "| `real_key` | documented |\n"
    )
    kinds, _stamp = parse_schema_doc(str(doc))
    assert kinds["thing"] == {"real_key", "kind"}


def test_baseline_round_trip(tmp_path):
    p = str(tmp_path / "b.json")
    base = Baseline([BaselineEntry("XF301", "x.py", "msg", reason="why")])
    base.save(p)
    loaded = Baseline.load(p)
    assert [(e.rule, e.path, e.message, e.reason) for e in loaded.entries] \
        == [("XF301", "x.py", "msg", "why")]
    # a missing file is an empty baseline, not an error
    assert Baseline.load(str(tmp_path / "nope.json")).entries == []


# ------------------------------------------------------------ CLI contract


def run_cli(*args, cwd=REPO_ROOT):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "xflowlint.py"),
         *args],
        capture_output=True, text=True, timeout=180, env=env, cwd=cwd)


def test_cli_exit_codes(tmp_path):
    bad = os.path.join(FIXTURES, "bad_jit_purity.py")
    # new findings -> 1
    r = run_cli(bad, "--no-baseline")
    assert r.returncode == 1 and "XF101" in r.stdout
    # everything baselined -> 0
    bl = str(tmp_path / "bl.json")
    r = run_cli(bad, "--write-baseline", "--baseline", bl)
    assert r.returncode == 0
    r = run_cli(bad, "--baseline", bl)
    assert r.returncode == 0 and "suppressed by baseline" in r.stdout
    # a fixed finding must leave the baseline -> 2 (baseline-shrink gate)
    clean = os.path.join(FIXTURES, "good_clean.py")
    r = run_cli(clean, "--baseline", bl)
    assert r.returncode == 2 and "STALE baseline entry" in r.stdout
    # --json carries the same verdicts
    r = run_cli(bad, "--no-baseline", "--json")
    data = json.loads(r.stdout)
    assert data["new"] and data["stale_baseline"] == []


def test_cli_full_repo_is_clean():
    """The whole tree lints green against the checked-in baseline —
    the same gate tools/smoke_lint.sh runs in CI."""
    r = run_cli()
    assert r.returncode == 0, (r.stdout, r.stderr)


def test_cli_unknown_rule_is_usage_error():
    assert run_cli("--rules", "XF999").returncode == 3


# ----------------------------------------- seeded violations (acceptance)

SEEDS = [
    # (rule, module to copy, seed snippet appended, marker)
    ("XF101",
     "xflow_tpu/models/predict.py",
     "\nimport jax as _jax, time as _time\n\n\n"
     "@_jax.jit\ndef _seeded(x):\n"
     "    return x + _time.perf_counter()  # SEED\n",
     "SEED"),
    ("XF201",
     "xflow_tpu/models/predict.py",
     "\nimport jax as _jax\n\n\ndef _seeded(xs):\n"
     "    for _x in xs:\n"
     "        _jax.jit(lambda v: v)(_x)  # SEED\n",
     "SEED"),
    ("XF301",
     "xflow_tpu/serve/metrics.py",
     "\nimport threading as _th\n\n\nclass _Seeded:\n"
     "    def __init__(self):\n"
     "        self.n = 0\n"
     "        _th.Thread(target=self._loop, daemon=True).start()\n"
     "    def _loop(self):\n"
     "        self.n += 1  # SEED\n"
     "    def bump(self):\n"
     "        self.n += 1\n",
     "SEED"),
    ("XF401",
     "xflow_tpu/serve/metrics.py",
     "\ndef _seeded(cfg: 'Config'):\n"
     "    return cfg.serve.windw_ms  # SEED\n",
     "SEED"),
    ("XF501",
     "xflow_tpu/serve/metrics.py",
     "\ndef _seeded(app):\n"
     "    app.append({'kind': 'serve', 'qqps': 1})  # SEED\n",
     "{'kind': 'serve'"),
]


@pytest.mark.parametrize("rule,module,snippet,marker",
                         SEEDS, ids=[s[0] for s in SEEDS])
def test_seeded_violation_in_real_module_caught(tmp_path, rule, module,
                                                snippet, marker):
    """ISSUE 10 acceptance: seed one violation of each rule class into a
    scratch copy of a REAL module; xflowlint reports the correct rule id
    at the correct file:line."""
    scratch = tmp_path / module
    scratch.parent.mkdir(parents=True, exist_ok=True)
    src = open(os.path.join(REPO_ROOT, module)).read()
    shutil.copy(os.path.join(REPO_ROOT, module), scratch)
    # the scratch copy must be CLEAN before seeding (real modules are)
    assert lint(str(scratch)) == [], "unseeded copy must lint clean"
    seeded_src = src + snippet
    scratch.write_text(seeded_src)
    want_line = next(i for i, ln in enumerate(seeded_src.splitlines(), 1)
                     if marker in ln)
    findings = lint(str(scratch))
    assert findings and {f.rule for f in findings} == {rule}, findings
    assert want_line in {f.line for f in findings}
    assert findings[0].path.endswith(os.path.basename(module))


# ----------------------------------------------------- schema/config seams


def test_schema_doc_parser_covers_every_shipped_kind():
    from xflow_tpu.analysis.passes.schema_drift import parse_schema_doc

    kinds, stamp = parse_schema_doc(
        os.path.join(REPO_ROOT, "docs", "OBSERVABILITY.md"))
    for kind in ("compile", "serve", "span", "heartbeat", "watchdog"):
        assert kind in kinds, f"doc lost its {kind} schema table"
    assert {"ts", "rank", "run_id", "gen", "world"} <= stamp
    assert "qps" in kinds["serve"] and "flagged_rank" in kinds["watchdog"]
    assert "dur_ms" in kinds["span"] and "op_scopes" in kinds["compile"]


def test_config_tree_parser_matches_dataclasses():
    from xflow_tpu.analysis.passes.config_keys import ConfigTree

    tree = ConfigTree.parse(os.path.join(REPO_ROOT, "xflow_tpu",
                                         "config.py"))
    assert set(tree.sections) == {"model", "optim", "data", "mesh",
                                  "train", "serve"}
    assert tree.resolve(("train", "log_every"))[0] == "ok"
    assert tree.resolve(("optim", "ftrl", "alpha"))[0] == "ok"
    assert tree.resolve(("num_slots",))[0] == "ok"  # Config property
    assert tree.resolve(("train", "nope"))[0] == "bad"
    assert tree.class_to_path["ServeConfig"] == ("serve",)


def test_dead_key_reported_only_on_full_tree(tmp_path):
    """XF402 needs the whole tree: partial lints must not scream."""
    findings = lint("good_clean.py", rules=["XF402"])
    assert findings == []


# --------------------------------------------------------------- smoke gate


def test_smoke_lint_script(tmp_path):
    """tools/smoke_lint.sh: repo lint green, fixture corpus fires,
    baseline growth/shrink mechanics, seeded-violation drill, ruff
    layer when available — runnable standalone and from CI."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        ["bash", os.path.join(REPO_ROOT, "tools", "smoke_lint.sh"),
         str(tmp_path / "work")],
        capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "smoke_lint: OK" in r.stdout
