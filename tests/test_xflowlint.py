"""xflowlint (xflow_tpu/analysis, tools/xflowlint.py,
tools/smoke_lint.sh): the fixture corpus proves every rule fires on
known-bad code — including the resurrected pre-PR 8 unlocked-appender
bug — and stays silent on the fixed shapes; suppression, baseline, and
CLI exit-code semantics are pinned; seeding a violation of each rule
class into a scratch copy of a REAL module is caught with the correct
rule id and file:line (the ISSUE 10 acceptance drill)."""

import json
import os
import re
import shutil
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from xflow_tpu.analysis.core import (  # noqa: E402
    Baseline, BaselineEntry, Finding, Module, Project, run_passes,
)

FIXTURES = os.path.join(REPO_ROOT, "tests", "fixtures", "xflowlint")


def lint(*paths, root=REPO_ROOT, rules=None):
    project = Project.load(root, [os.path.join(FIXTURES, p) if not
                                  os.path.isabs(p) else p for p in paths])
    only = set(rules) if rules else None
    return run_passes(project, only_rules=only)


def rules_of(findings):
    return sorted({f.rule for f in findings})


def marker_lines(fixture, rule):
    """Lines in a fixture carrying a `# XFnnn:` expectation marker."""
    out = set()
    with open(os.path.join(FIXTURES, fixture)) as f:
        for i, line in enumerate(f, 1):
            if f"# {rule}:" in line:
                out.add(i)
    return out


# ------------------------------------------------------------ rule firing


def test_jit_purity_fixture_fires_on_every_marker():
    findings = lint("bad_jit_purity.py")
    assert rules_of(findings) == ["XF101"]
    assert {f.line for f in findings} == marker_lines(
        "bad_jit_purity.py", "XF101")
    # the PR 2 rule by name: perf_counter inside a jit body
    assert any("time.perf_counter" in f.message for f in findings)
    # RNG, print, global, scan-body, and traced-lambda variants all land
    blob = " ".join(f.message for f in findings)
    for needle in ("random.random", "print", "global mutation",
                   "numpy.random.seed", "time.time"):
        assert needle in blob, needle


def test_recompile_fixture_fires_all_three_rules():
    findings = lint("bad_recompile.py")
    by_rule = {r: [f for f in findings if f.rule == r]
               for r in rules_of(findings)}
    assert set(by_rule) == {"XF201", "XF202", "XF203"}
    assert {f.line for f in by_rule["XF201"]} == marker_lines(
        "bad_recompile.py", "XF201")
    assert {f.line for f in by_rule["XF202"]} == marker_lines(
        "bad_recompile.py", "XF202")
    assert {f.line for f in by_rule["XF203"]} == marker_lines(
        "bad_recompile.py", "XF203")


def test_lockset_fixture_retro_detects_pre_pr8_appender():
    """The resurrected pre-PR 8 JsonlAppender (no append lock, health
    thread + handler threads) must fire on every unlocked mutation of
    the shared file-handle state."""
    findings = lint("bad_lockset.py")
    assert rules_of(findings) == ["XF301"]
    attrs = {re.search(r"`self\.(\w+)`", f.message).group(1)
             for f in findings}
    # the lazy-open handle and its byte counter are the bug
    assert "_f" in attrs and "_size" in attrs
    # every finding names both regions that collide
    for f in findings:
        assert "thread:_health_loop" in f.message
        assert "external" in f.message


def test_lockset_silent_on_fixed_appender():
    assert lint("good_lockset.py") == []


def test_config_fixture_fires_on_every_marker():
    findings = lint("bad_config.py")
    assert rules_of(findings) == ["XF401"]
    assert {f.line for f in findings} == marker_lines(
        "bad_config.py", "XF401")
    blob = " ".join(f.message for f in findings)
    for needle in ("train.lag_every", "sreve", "windw_ms", "train.epocs",
                   "serve.max_bach"):
        assert needle in blob, needle


def test_schema_fixture_fires_drift_and_unknown_kind():
    findings = lint("bad_schema.py")
    assert rules_of(findings) == ["XF501", "XF502"]
    msgs = " ".join(f.message for f in findings)
    assert "queue_wait_p50ms" in msgs  # drifted serve window key
    assert "stepp" in msgs  # drift against a stamp-declared kind
    assert '"shadow"' in msgs  # unknown kind


def test_shell_fixture_fires_strict_mode_and_bad_key():
    findings = lint("bad_shell.sh")
    assert rules_of(findings) == ["XF401", "XF601"]
    (f601,) = [f for f in findings if f.rule == "XF601"]
    assert "-o pipefail" in f601.message
    (f401,) = [f for f in findings if f.rule == "XF401"]
    assert "train.log_evry" in f401.message


def test_hostsync_fixture_fires_on_every_marker():
    findings = lint("bad_hostsync.py")
    by_rule = {r: [f for f in findings if f.rule == r]
               for r in rules_of(findings)}
    assert set(by_rule) == {"XF110", "XF111"}
    assert {f.line for f in by_rule["XF110"]} == marker_lines(
        "bad_hostsync.py", "XF110")
    assert {f.line for f in by_rule["XF111"]} == marker_lines(
        "bad_hostsync.py", "XF111")
    blob = " ".join(f.message for f in findings)
    # explicit conversions, formatting, and the implicit branch all land
    for needle in ("float", "print", "f-string", "bool", "int",
                   "branch condition"):
        assert needle in blob, needle


def test_hostsync_one_behind_staged_read_is_exempt_by_construction():
    """The fixture's `staged` reads model the StepTimer discipline: the
    value was staged LAST iteration and a newer dispatch aged it — no
    suppression comment involved, the engine proves it stale. The
    post-run epilogue loop (dispatches nothing, only reads) is the
    other by-construction exemption: its syncs are mandatory one-time
    reads, not pipeline bubbles."""
    src = open(os.path.join(FIXTURES, "bad_hostsync.py")).read()
    exempt = {i for i, ln in enumerate(src.splitlines(), 1)
              if 'float(staged["loss"])' in ln or 'float(m[key])' in ln}
    assert len(exempt) == 2
    findings = lint("bad_hostsync.py")
    assert not (exempt & {f.line for f in findings})


def test_sharding_contract_fixture_fires_on_every_marker():
    findings = lint("bad_sharding_contract.py")
    by_rule = {r: [f for f in findings if f.rule == r]
               for r in rules_of(findings)}
    assert set(by_rule) == {"XF701", "XF702", "XF703"}
    for rule in by_rule:
        assert {f.line for f in by_rule[rule]} == marker_lines(
            "bad_sharding_contract.py", rule), rule
    (f701,) = by_rule["XF701"]
    assert "'tabel'" in f701.message and "data, table" in f701.message


def test_unrecorded_jit_fires_only_in_recorder_scoped_paths(tmp_path):
    """XF204 is scoped to the engine/serve modules where PR 7's
    CompileRecorder contract holds."""
    src = (
        "import jax\n"
        "def build(model):\n"
        "    def step(s, b):\n"
        "        return s\n"
        "    return jax.jit(step)\n"
    )
    scoped = tmp_path / "xflow_tpu" / "serve"
    scoped.mkdir(parents=True)
    (scoped / "newmod.py").write_text(src)
    unscoped = tmp_path / "xflow_tpu" / "data"
    unscoped.mkdir(parents=True)
    (unscoped / "newmod.py").write_text(src)
    findings = lint(str(scoped / "newmod.py"),
                    str(unscoped / "newmod.py"), root=str(tmp_path))
    assert rules_of(findings) == ["XF204"]
    assert [f.path for f in findings] == ["xflow_tpu/serve/newmod.py"]
    assert findings[0].line == 5


# ------------------------------------------------- precision (no false fire)


def test_loop_var_static_check_is_scope_local(tmp_path):
    """A parameter named like an unrelated loop variable in another
    function is NOT a loop variable (XF202 stays quiet)."""
    mod = tmp_path / "m.py"
    mod.write_text(
        "import jax\n\n\ndef f(x, n):\n    return x * n\n\n\n"
        "def other(xs):\n    for k in xs:\n        print(k)\n\n\n"
        "g = jax.jit(f, static_argnums=(1,))\n\n\n"
        "def call(k):\n    return g(1.0, k)\n"
    )
    assert lint(str(mod), rules=["XF202"]) == []


def test_loop_var_after_loop_is_single_valued(tmp_path):
    """XF202 retrofit regression pin: a loop variable read AFTER its
    loop is one value per outer execution — the old name-set heuristic
    flagged it (the documented scope-locality caveat); the dataflow
    engine must not."""
    mod = tmp_path / "m.py"
    mod.write_text(
        "import jax\n\n\ndef f(x, n):\n    return x * n\n\n\n"
        "g = jax.jit(f, static_argnums=(1,))\n\n\n"
        "def post_loop(x, xs):\n"
        "    for k in xs:\n        x = x + k\n"
        "    return g(x, k)\n"
    )
    assert lint(str(mod), rules=["XF202"]) == []


def test_loop_var_copied_through_alias_is_caught(tmp_path):
    """XF202 retrofit gain: `n = k; g(x, n)` inside the loop varies per
    iteration exactly like passing `k` directly — the name heuristic
    missed it, the dataflow engine follows the assignment."""
    mod = tmp_path / "m.py"
    mod.write_text(
        "import jax\n\n\ndef f(x, n):\n    return x * n\n\n\n"
        "g = jax.jit(f, static_argnums=(1,))\n\n\n"
        "def aliased(x, xs):\n"
        "    for k in xs:\n"
        "        n = k\n"
        "        x = g(x, n)\n"
        "    return x\n"
    )
    findings = lint(str(mod), rules=["XF202"])
    assert [f.rule for f in findings] == ["XF202"]
    assert findings[0].line == 14  # the call site, not the alias line


def test_loop_var_rebound_to_constant_is_clean(tmp_path):
    """XF202 retrofit: rebinding the name to a constant inside the loop
    kills the loop-variance fact (flow-sensitivity, not name matching)."""
    mod = tmp_path / "m.py"
    mod.write_text(
        "import jax\n\n\ndef f(x, n):\n    return x * n\n\n\n"
        "g = jax.jit(f, static_argnums=(1,))\n\n\n"
        "def rebound(x, xs):\n"
        "    for k in xs:\n"
        "        k = 3\n"
        "        x = g(x, k)\n"
        "    return x\n"
    )
    assert lint(str(mod), rules=["XF202"]) == []


def test_donated_read_in_loop_without_rebind_is_caught(tmp_path):
    """XF702: the donate-then-reuse loop (forgot `state = step(state)`)
    — the second iteration passes an invalidated buffer."""
    mod = tmp_path / "m.py"
    mod.write_text(
        "import jax\n\n\ndef run(step, state, batches):\n"
        "    jitted = jax.jit(step, donate_argnums=(0,))\n"
        "    outs = []\n"
        "    for b in batches:\n"
        "        outs.append(jitted(state, b))\n"
        "    return outs\n"
    )
    findings = lint(str(mod), rules=["XF702"])
    assert findings and {f.rule for f in findings} == {"XF702"}
    # the rebound form is the fix and must be clean
    mod.write_text(
        "import jax\n\n\ndef run(step, state, batches):\n"
        "    jitted = jax.jit(step, donate_argnums=(0,))\n"
        "    for b in batches:\n"
        "        state, m = jitted(state, b)\n"
        "    return state\n"
    )
    assert lint(str(mod), rules=["XF702"]) == []


def test_undonated_eval_step_is_not_flagged(tmp_path):
    """XF703 keys on the TrainState parameter: eval/predict jits take
    read-only `tables` and must NOT be asked to donate them."""
    mod = tmp_path / "m.py"
    mod.write_text(
        "import jax\n\n\ndef make_eval():\n"
        "    def eval_step(tables, batch):\n"
        "        return tables\n"
        "    return jax.jit(eval_step)\n"
    )
    assert lint(str(mod), rules=["XF703"]) == []


def test_lockset_private_thread_only_helper_not_external(tmp_path):
    """A private helper only the spawned thread calls is single-
    threaded — no finding; the same helper called from a PUBLIC method
    still fires."""
    base = (
        "import threading\n\n\nclass W:\n"
        "    def __init__(self):\n"
        "        self._buf = []\n"
        "        threading.Thread(target=self._run, daemon=True).start()\n\n"
        "    def _run(self):\n        self._flush()\n\n"
        "    def _flush(self):\n        self._buf = []\n"
    )
    mod = tmp_path / "w.py"
    mod.write_text(base)
    assert lint(str(mod), rules=["XF301"]) == []
    mod.write_text(base + "\n    def drain(self):\n        self._flush()\n")
    assert [f.rule for f in lint(str(mod), rules=["XF301"])] == ["XF301"]


def test_shell_strict_mode_must_precede_commands(tmp_path):
    """`set -euo pipefail` AFTER fallible commands protects nothing."""
    sh = tmp_path / "late.sh"
    sh.write_text("#!/usr/bin/env bash\nrm -rf \"$1\"\nset -euo pipefail\n")
    assert [f.rule for f in lint(str(sh))] == ["XF601"]


def test_shell_comment_mentions_of_keys_ignored(tmp_path):
    sh = tmp_path / "c.sh"
    sh.write_text("#!/usr/bin/env bash\nset -euo pipefail\n"
                  "# historical note: serve.windw_ms=3 was renamed\n"
                  "true\n")
    assert lint(str(sh)) == []


# ------------------------------------------------- suppression / negatives


def test_inline_and_file_suppressions():
    assert lint("suppress_line.py") == []
    assert lint("suppress_file.py") == []
    # the same code without the directive DOES fire (the suppression is
    # what silences it, not a pass gap)
    mod = Module("x.py", "x.py",
                 open(os.path.join(FIXTURES, "suppress_line.py")).read()
                 .replace("# xflowlint: disable=XF101", ""))
    assert not mod.line_suppress


def test_clean_fixture_is_clean():
    assert lint("good_clean.py") == []


# -------------------------------------------------------- baseline model


def _finding(rule="XF101", path="a.py", line=3, message="m"):
    return Finding(rule=rule, path=path, line=line, message=message)


def test_baseline_split_new_known_stale():
    base = Baseline([BaselineEntry("XF101", "a.py", "m", reason="legacy")])
    new, known, stale = base.split([_finding(), _finding(line=9)])
    # line numbers are NOT part of the fingerprint: both match
    assert not new and len(known) == 2 and not stale
    new, known, stale = base.split([_finding(message="other")])
    assert len(new) == 1 and not known and len(stale) == 1


def test_baseline_staleness_scoped_to_selected_rules():
    """`--rules XF301` skips the config pass — an XF401 baseline entry
    must not read as stale just because its pass never ran."""
    base = Baseline([BaselineEntry("XF401", "a.py", "m", reason="legacy")])
    _new, _known, stale = base.split([], only_rules={"XF301"})
    assert stale == []
    _new, _known, stale = base.split([], only_rules={"XF401"})
    assert len(stale) == 1
    _new, _known, stale = base.split([])  # full run: stale for real
    assert len(stale) == 1


def test_syntax_error_respects_rules_filter_and_suppression(tmp_path):
    bad = tmp_path / "broken.py"
    bad.write_text("def f(:\n")
    findings = lint(str(bad))
    assert rules_of(findings) == ["XF001"]
    # --rules excluding XF001 filters it
    assert lint(str(bad), rules=["XF301"]) == []
    # disable-file works even though the file never parsed
    bad.write_text("# xflowlint: disable-file=XF001 — generated junk\n"
                   "def f(:\n")
    assert lint(str(bad)) == []


def test_shell_all_wildcard_suppression(tmp_path):
    from xflow_tpu.analysis.core import ShellScript

    sh = ShellScript("x.sh", "x.sh",
                     "# xflowlint: disable-file=all\necho hi\n")
    assert sh.suppressed("XF601", 2)  # Module and ShellScript agree


def test_write_baseline_refuses_partial_scan_and_keeps_reasons(tmp_path):
    bad = os.path.join(FIXTURES, "bad_jit_purity.py")
    # partial path set + no explicit --baseline: refuse (3), never
    # clobber the repo-wide baseline with a partial scan
    r = run_cli(bad, "--write-baseline", "--reason", "r")
    assert r.returncode == 3 and "PARTIAL" in r.stderr
    # an audited reason survives regeneration of the same target, even
    # when a different --reason is supplied for genuinely-new entries
    bl = str(tmp_path / "bl.json")
    assert run_cli(bad, "--write-baseline", "--baseline", bl,
                   "--reason", "first write").returncode == 0
    base = Baseline.load(bl)
    assert base.entries
    assert all(e.reason == "first write" for e in base.entries)
    base.entries[0].reason = "audited: fixture keeps this on purpose"
    base.save(bl)
    assert run_cli(bad, "--write-baseline", "--baseline", bl,
                   "--reason", "regen").returncode == 0
    kept = Baseline.load(bl)
    assert any(e.reason == "audited: fixture keeps this on purpose"
               for e in kept.entries)


def test_write_baseline_requires_reason_for_new_entries(tmp_path):
    """The ISSUE 15 placeholder-leak fix: NEW entries without --reason
    are refused (exit 3) instead of landing as 'TODO: justify or fix'."""
    bad = os.path.join(FIXTURES, "bad_jit_purity.py")
    bl = str(tmp_path / "bl.json")
    r = run_cli(bad, "--write-baseline", "--baseline", bl)
    assert r.returncode == 3 and "--reason" in r.stderr
    assert not os.path.exists(bl)  # refused writes leave no file


def test_baseline_placeholder_reason_fails_audit(tmp_path):
    """A checked-in baseline entry still carrying the placeholder
    reason fails the gate with exit 3 (usage error, not a lint
    verdict) and names the entry."""
    bad = os.path.join(FIXTURES, "bad_jit_purity.py")
    bl = tmp_path / "bl.json"
    base = Baseline([BaselineEntry("XF101", "a.py", "m",
                                   reason="TODO: justify or fix")])
    base.save(str(bl))
    r = run_cli(bad, "--baseline", str(bl))
    assert r.returncode == 3
    assert "placeholder" in r.stderr and "a.py" in r.stderr


def test_write_baseline_refuses_rule_scoped_scan():
    """--rules + --write-baseline would drop every other rule's audited
    entries — refused like the partial-path case."""
    r = run_cli("--rules", "XF301", "--write-baseline")
    assert r.returncode == 3 and "--rules" in r.stderr


def test_unrecorded_jit_catches_decorator_form(tmp_path):
    """`@jax.jit` (and `@partial(jax.jit, ...)`) in a recorder-scoped
    module bypasses compile accounting exactly like the call form."""
    scoped = tmp_path / "xflow_tpu" / "serve"
    scoped.mkdir(parents=True)
    (scoped / "m.py").write_text(
        "import jax\nfrom functools import partial\n\n\n"
        "@jax.jit\ndef step(s):\n    return s\n\n\n"
        "@partial(jax.jit, donate_argnums=(0,))\ndef step2(s):\n"
        "    return s\n"
    )
    findings = lint(str(scoped / "m.py"), root=str(tmp_path))
    assert [f.rule for f in findings] == ["XF204", "XF204"]
    # lineno of a decorated FunctionDef is the `def` line
    assert {f.line for f in findings} == {6, 11}


def test_schema_doc_parser_ignores_fenced_blocks(tmp_path):
    from xflow_tpu.analysis.passes.schema_drift import parse_schema_doc

    doc = tmp_path / "d.md"
    doc.write_text(
        '## Records (`kind="thing"`)\n\n'
        "```bash\n"
        "# this comment must not read as a heading\n"
        "| `not_a_key` | fenced tables are examples |\n"
        "```\n\n"
        "| field | meaning |\n"
        "|---|---|\n"
        "| `real_key` | documented |\n"
    )
    kinds, _stamp = parse_schema_doc(str(doc))
    assert kinds["thing"] == {"real_key", "kind"}


def test_baseline_round_trip(tmp_path):
    p = str(tmp_path / "b.json")
    base = Baseline([BaselineEntry("XF301", "x.py", "msg", reason="why")])
    base.save(p)
    loaded = Baseline.load(p)
    assert [(e.rule, e.path, e.message, e.reason) for e in loaded.entries] \
        == [("XF301", "x.py", "msg", "why")]
    # a missing file is an empty baseline, not an error
    assert Baseline.load(str(tmp_path / "nope.json")).entries == []


# ------------------------------------------------------------ CLI contract


def run_cli(*args, cwd=REPO_ROOT):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "xflowlint.py"),
         *args],
        capture_output=True, text=True, timeout=180, env=env, cwd=cwd)


def test_cli_exit_codes(tmp_path):
    bad = os.path.join(FIXTURES, "bad_jit_purity.py")
    # new findings -> 1
    r = run_cli(bad, "--no-baseline")
    assert r.returncode == 1 and "XF101" in r.stdout
    # everything baselined -> 0
    bl = str(tmp_path / "bl.json")
    r = run_cli(bad, "--write-baseline", "--baseline", bl,
                "--reason", "exit-code drill")
    assert r.returncode == 0
    r = run_cli(bad, "--baseline", bl)
    assert r.returncode == 0 and "suppressed by baseline" in r.stdout
    # a fixed finding must leave the baseline -> 2 (baseline-shrink gate)
    clean = os.path.join(FIXTURES, "good_clean.py")
    r = run_cli(clean, "--baseline", bl)
    assert r.returncode == 2 and "STALE baseline entry" in r.stdout
    # --json carries the same verdicts
    r = run_cli(bad, "--no-baseline", "--json")
    data = json.loads(r.stdout)
    assert data["new"] and data["stale_baseline"] == []


def test_cli_full_repo_is_clean():
    """The whole tree lints green against the checked-in baseline —
    the same gate tools/smoke_lint.sh runs in CI."""
    r = run_cli()
    assert r.returncode == 0, (r.stdout, r.stderr)


def test_cli_unknown_rule_is_usage_error():
    assert run_cli("--rules", "XF999").returncode == 3


# ------------------------------------------------- engine-contract matrix


def test_contract_artifact_checked_in_and_byte_stable():
    """tools/engine_contracts.json: covers all four engine builders,
    the AST sections match a fresh extraction, two consecutive
    extractions render byte-identically (ISSUE 14 acceptance), and the
    v2 jaxpr section (ISSUE 15) is present and program-complete."""
    from xflow_tpu.analysis.ir import PROGRAMS
    from xflow_tpu.analysis.passes.sharding_contract import (
        ENGINE_MODULES, extract_contracts, render_artifact,
    )

    project = Project.load(REPO_ROOT)
    r1 = render_artifact(extract_contracts(project))
    r2 = render_artifact(extract_contracts(Project.load(REPO_ROOT)))
    assert r1 == r2, "extraction is not deterministic"
    on_disk = json.loads(open(os.path.join(
        REPO_ROOT, "tools", "engine_contracts.json")).read())
    # contracts v2: the jaxpr section rides the same artifact — every
    # IR program with its op histogram / gather-scatter counts / dtype
    # census / cost estimates
    ir = on_disk.pop("ir_programs")
    assert set(ir["programs"]) == {p[0] for p in PROGRAMS}
    for key, prog in ir["programs"].items():
        assert prog["op_histogram"], key
        assert prog["dtype_census"], key
        assert prog["cost"] and prog["cost"]["flops"] > 0, key
        if key.startswith("train_step"):
            assert prog["donated_args"] == [0], key
            assert prog["scatters"] >= 1, key
    assert render_artifact(on_disk) == r1, (
        "checked-in engine_contracts.json AST sections are stale — "
        "regenerate with tools/xflowlint.py --write-contracts and "
        "review the diff")
    data = json.loads(r1)
    assert set(data["engines"]) == set(ENGINE_MODULES)
    assert data["declared_mesh_axes"] == ["data", "table"]


def test_contract_matrix_covers_known_invariants():
    """Spot-check the matrix against facts the builders guarantee
    today: every train program donates the state, every engine covers
    the core trace scopes, the sorted-sharded table rides
    P('table', None)."""
    data = json.load(open(os.path.join(REPO_ROOT, "tools",
                                       "engine_contracts.json")))
    train_programs = 0
    for rel, eng in data["engines"].items():
        for name, prog in eng["programs"].items():
            if name.startswith("train_step"):
                train_programs += 1
                assert prog["donate_argnums"] == [0], (rel, name)
    assert train_programs == 4  # one train program per builder
    ss = data["engines"]["xflow_tpu/parallel/sorted_sharded.py"]
    assert ss["leaf_specs"]["wv"] == ["NamedSharding(P('table', None))"]
    assert ss["leaf_specs"]["wv.n"] == ss["leaf_specs"]["wv.z"]
    for rel, eng in data["engines"].items():
        if rel == "xflow_tpu/parallel/train_step.py":
            continue  # inherits the shared step's scopes by delegation
        assert {"gather", "loss", "grad", "optimizer"} <= set(eng["scopes"]), rel


def test_cli_check_contracts_green_then_drift_exits_4(tmp_path):
    """--check-contracts: 0 on a faithful tree, 4 (distinct from
    finding growth) when a builder's contract changed without
    regenerating the artifact."""
    root = tmp_path / "tree"
    for rel in ("xflow_tpu/train/step.py", "xflow_tpu/parallel/mesh.py",
                "xflow_tpu/parallel/train_step.py",
                "xflow_tpu/parallel/sorted_sharded.py",
                "xflow_tpu/parallel/sorted_fullshard.py",
                "tools/engine_contracts.json"):
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(os.path.join(REPO_ROOT, rel), dst)
    r = run_cli("--root", str(root), "--check-contracts")
    assert r.returncode == 0, (r.stdout, r.stderr)
    # drop the donation from one builder: contract drift, exit 4
    sf = root / "xflow_tpu/parallel/sorted_sharded.py"
    sf.write_text(sf.read_text().replace("donate_argnums=(0,),", ""))
    r = run_cli("--root", str(root), "--check-contracts")
    assert r.returncode == 4 and "CONTRACT DRIFT" in r.stderr


def test_xf704_scope_drift_across_builders(tmp_path):
    """Renaming one builder's 'optimizer' scope (present in every other
    builder) fires XF704 on that builder only."""
    root = tmp_path / "tree"
    for rel in ("xflow_tpu/train/step.py", "xflow_tpu/parallel/mesh.py",
                "xflow_tpu/parallel/train_step.py",
                "xflow_tpu/parallel/sorted_sharded.py",
                "xflow_tpu/parallel/sorted_fullshard.py"):
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(os.path.join(REPO_ROOT, rel), dst)
    project = Project.load(str(root))
    assert [f for f in run_passes(project) if f.rule == "XF704"] == []
    sf = root / "xflow_tpu/parallel/sorted_sharded.py"
    sf.write_text(sf.read_text().replace(
        'named_scope("optimizer")', 'named_scope("optimzer")'))
    findings = [f for f in run_passes(Project.load(str(root)))
                if f.rule == "XF704"]
    assert len(findings) == 1
    assert findings[0].path == "xflow_tpu/parallel/sorted_sharded.py"
    assert "'optimizer'" in findings[0].message


def test_xf704_silent_on_partial_scan_without_shared_step():
    """A partial scan holding the parallel builders but NOT the shared
    single-device step must not false-fire XF704 on the delegating
    GSPMD builder: the shared step's scopes load from disk (like the
    mesh axes do)."""
    findings = lint(os.path.join(REPO_ROOT, "xflow_tpu", "parallel"))
    assert [f for f in findings if f.rule == "XF704"] == []


def test_xf704_partial_scan_matches_full_tree_verdict():
    """The comparison roster is always the full builder set (missing
    builders load from disk), so the exact --changed file set that used
    to false-fire — the shared step plus ONE parallel builder, where
    'every other builder' collapsed to the step's scope superset —
    stays clean, matching the full-tree verdict."""
    findings = lint(
        os.path.join(REPO_ROOT, "xflow_tpu", "train", "step.py"),
        os.path.join(REPO_ROOT, "xflow_tpu", "parallel",
                     "sorted_sharded.py"))
    assert [f for f in findings if f.rule == "XF704"] == []


def test_hostsync_jit_construction_does_not_age(tmp_path):
    """Constructing a jit callable dispatches nothing: it must not age
    a same-iteration device value into exemption (XF110 stays live)."""
    mod = tmp_path / "m.py"
    mod.write_text(
        "import jax\n\n\nclass T:\n"
        "    def _fit(self, bs):\n"
        "        for b in bs:\n"
        "            s, m = self.train_step(None, b)\n"
        "            fn = jax.jit(lambda v: v)\n"
        "            x = float(m['loss'])\n"
    )
    findings = lint(str(mod), rules=["XF110"])
    assert [f.rule for f in findings] == ["XF110"]
    assert findings[0].line == 9


def test_xf704_intra_builder_leaf_spec_disagreement(tmp_path):
    """One builder declaring two different shardings for the same table
    leaf is contract drift between its own programs."""
    root = tmp_path / "tree"
    for rel in ("xflow_tpu/train/step.py", "xflow_tpu/parallel/mesh.py",
                "xflow_tpu/parallel/train_step.py",
                "xflow_tpu/parallel/sorted_sharded.py",
                "xflow_tpu/parallel/sorted_fullshard.py"):
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(os.path.join(REPO_ROOT, rel), dst)
    sf = root / "xflow_tpu/parallel/sorted_sharded.py"
    sf.write_text(
        sf.read_text()
        + "\n\n_drifted = {\"wv\": NamedSharding(None, P(None, None))}\n"
    )
    findings = [f for f in run_passes(Project.load(str(root)))
                if f.rule == "XF704"]
    assert len(findings) == 1
    assert "'wv'" in findings[0].message


# ------------------------------------------------------ CLI: jobs/changed


def test_jobs_fanout_output_identical():
    """-j N must produce byte-identical findings to the serial sweep
    (the pre-commit speed path cannot change verdicts)."""
    bad = os.path.join(FIXTURES, "bad_hostsync.py")
    bad2 = os.path.join(FIXTURES, "bad_sharding_contract.py")
    serial = run_cli(bad, bad2, "--no-baseline", "--json")
    fanned = run_cli(bad, bad2, "--no-baseline", "--json", "--jobs", "2")
    assert serial.returncode == fanned.returncode == 1
    assert json.loads(serial.stdout)["new"] == json.loads(fanned.stdout)["new"]


def test_changed_lints_only_git_changed_files(tmp_path):
    """--changed in a scratch git repo: clean tree -> nothing to lint;
    a modified module -> linted and gated."""
    import subprocess as sp

    root = tmp_path / "repo"
    (root / "xflow_tpu").mkdir(parents=True)
    (root / "xflow_tpu" / "mod.py").write_text("x = 1\n")
    env = dict(os.environ,
               GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
               GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t")
    for cmd in (["git", "init", "-q"], ["git", "add", "-A"],
                ["git", "commit", "-qm", "seed"]):
        sp.run(cmd, cwd=root, env=env, check=True, capture_output=True)
    r = run_cli("--root", str(root), "--changed")
    assert r.returncode == 0 and "no lintable changed files" in r.stderr
    # introduce a finding in a tracked file -> --changed catches it
    (root / "xflow_tpu" / "mod.py").write_text(
        "import jax, time\n\n\n@jax.jit\ndef f(x):\n"
        "    return x + time.time()\n")
    r = run_cli("--root", str(root), "--changed")
    assert r.returncode == 1 and "XF101" in r.stdout


def test_partial_scan_never_stales_full_tree_only_rules(tmp_path):
    """XF402 (dead-key) only runs on full-tree scans: a partial scan
    that covers the entry's file must still not call it stale (it
    would block the --changed pre-commit path with a bogus exit 2)."""
    bl = tmp_path / "bl.json"
    base = Baseline([BaselineEntry(
        "XF402", "xflow_tpu/config.py", "m", reason="accepted dead key")])
    base.save(str(bl))
    r = run_cli(os.path.join(REPO_ROOT, "xflow_tpu", "config.py"),
                "--baseline", str(bl))
    assert r.returncode == 0, (r.stdout, r.stderr)


def test_baseline_staleness_scoped_to_scanned_paths():
    """A --changed-style partial scan must not call entries in
    untouched files stale (Baseline.split only_paths)."""
    base = Baseline([BaselineEntry("XF101", "a.py", "m", reason="legacy")])
    _new, _known, stale = base.split([], only_paths={"b.py"})
    assert stale == []
    _new, _known, stale = base.split([], only_paths={"a.py"})
    assert len(stale) == 1


# ----------------------------------------- seeded violations (acceptance)

SEEDS = [
    # (rule, module to copy, seed snippet appended, marker)
    ("XF101",
     "xflow_tpu/models/predict.py",
     "\nimport jax as _jax, time as _time\n\n\n"
     "@_jax.jit\ndef _seeded(x):\n"
     "    return x + _time.perf_counter()  # SEED\n",
     "SEED"),
    ("XF201",
     "xflow_tpu/models/predict.py",
     "\nimport jax as _jax\n\n\ndef _seeded(xs):\n"
     "    for _x in xs:\n"
     "        _jax.jit(lambda v: v)(_x)  # SEED\n",
     "SEED"),
    ("XF301",
     "xflow_tpu/serve/metrics.py",
     "\nimport threading as _th\n\n\nclass _Seeded:\n"
     "    def __init__(self):\n"
     "        self.n = 0\n"
     "        _th.Thread(target=self._loop, daemon=True).start()\n"
     "    def _loop(self):\n"
     "        self.n += 1  # SEED\n"
     "    def bump(self):\n"
     "        self.n += 1\n",
     "SEED"),
    ("XF401",
     "xflow_tpu/serve/metrics.py",
     "\ndef _seeded(cfg: 'Config'):\n"
     "    return cfg.serve.windw_ms  # SEED\n",
     "SEED"),
    ("XF501",
     "xflow_tpu/serve/metrics.py",
     "\ndef _seeded(app):\n"
     "    app.append({'kind': 'serve', 'qqps': 1})  # SEED\n",
     "{'kind': 'serve'"),
    ("XF110",
     "xflow_tpu/train/trainer.py",
     "\n\nclass _SeededSync:\n"
     "    def _fit(self, batches):\n"
     "        state = None\n"
     "        for b in batches:\n"
     "            state, m = self.train_step(state, b)\n"
     "            print(float(m['loss']))  # SEED\n",
     "SEED"),
    ("XF111",
     "xflow_tpu/train/trainer.py",
     "\n\nclass _SeededBranch:\n"
     "    def _fit(self, batches):\n"
     "        state = None\n"
     "        for b in batches:\n"
     "            state, m = self.train_step(state, b)\n"
     "            if m['update_ok']:  # SEED\n"
     "                break\n",
     "SEED"),
    ("XF701",
     "xflow_tpu/parallel/mesh.py",
     "\n\ndef _seeded_axis(mesh):\n"
     "    return NamedSharding(mesh, P('tabel', None))  # SEED\n",
     "SEED"),
    ("XF702",
     "xflow_tpu/parallel/mesh.py",
     "\n\ndef _seeded_donated(step, state, b):\n"
     "    jitted = jax.jit(step, donate_argnums=(0,))\n"
     "    out = jitted(state, b)\n"
     "    return out, state  # SEED\n",
     "SEED"),
    ("XF703",
     "xflow_tpu/parallel/mesh.py",
     "\n\ndef _seeded_nodonate():\n"
     "    def train_step(state, batch):\n"
     "        return state\n\n"
     "    return jax.jit(train_step)  # SEED\n",
     "SEED"),
]


@pytest.mark.parametrize("rule,module,snippet,marker",
                         SEEDS, ids=[s[0] for s in SEEDS])
def test_seeded_violation_in_real_module_caught(tmp_path, rule, module,
                                                snippet, marker):
    """ISSUE 10 acceptance: seed one violation of each rule class into a
    scratch copy of a REAL module; xflowlint reports the correct rule id
    at the correct file:line."""
    scratch = tmp_path / module
    scratch.parent.mkdir(parents=True, exist_ok=True)
    src = open(os.path.join(REPO_ROOT, module)).read()
    shutil.copy(os.path.join(REPO_ROOT, module), scratch)
    # the scratch copy must be CLEAN before seeding (real modules are)
    assert lint(str(scratch)) == [], "unseeded copy must lint clean"
    seeded_src = src + snippet
    scratch.write_text(seeded_src)
    want_line = next(i for i, ln in enumerate(seeded_src.splitlines(), 1)
                     if marker in ln)
    findings = lint(str(scratch))
    assert findings and {f.rule for f in findings} == {rule}, findings
    assert want_line in {f.line for f in findings}
    assert findings[0].path.endswith(os.path.basename(module))


# ----------------------------------------------------- schema/config seams


def test_schema_doc_parser_covers_every_shipped_kind():
    from xflow_tpu.analysis.passes.schema_drift import parse_schema_doc

    kinds, stamp = parse_schema_doc(
        os.path.join(REPO_ROOT, "docs", "OBSERVABILITY.md"))
    for kind in ("compile", "serve", "span", "heartbeat", "watchdog"):
        assert kind in kinds, f"doc lost its {kind} schema table"
    assert {"ts", "rank", "run_id", "gen", "world"} <= stamp
    assert "qps" in kinds["serve"] and "flagged_rank" in kinds["watchdog"]
    assert "dur_ms" in kinds["span"] and "op_scopes" in kinds["compile"]


def test_config_tree_parser_matches_dataclasses():
    from xflow_tpu.analysis.passes.config_keys import ConfigTree

    tree = ConfigTree.parse(os.path.join(REPO_ROOT, "xflow_tpu",
                                         "config.py"))
    assert set(tree.sections) == {"model", "optim", "data", "mesh",
                                  "train", "serve", "sync"}
    assert tree.resolve(("train", "log_every"))[0] == "ok"
    assert tree.resolve(("optim", "ftrl", "alpha"))[0] == "ok"
    assert tree.resolve(("num_slots",))[0] == "ok"  # Config property
    assert tree.resolve(("train", "nope"))[0] == "bad"
    assert tree.class_to_path["ServeConfig"] == ("serve",)


def test_dead_key_reported_only_on_full_tree(tmp_path):
    """XF402 needs the whole tree: partial lints must not scream."""
    findings = lint("good_clean.py", rules=["XF402"])
    assert findings == []


# ---------------------------------------------- IR tier (XF801-XF804)


def _toy_facts(**program_overrides):
    """Synthetic IR facts with one program, for rule-function tests."""
    prog = {
        "engine": "xflow_tpu/train/step.py",
        "recorder_name": "train_step",
        "op_histogram": {"gather": 1},
        "dtype_census": {"float32": 3},
        "gathers": 1,
        "scatters": 1,
        "chains": [],
        "converts": [],
        "scans": [],
        "donated_args": [0],
        "has_sharding_annotations": False,
        "cost": {"flops": 1.0, "bytes_accessed": 1.0},
        "config": {}, "batch": "rowmajor",
    }
    prog.update(program_overrides)
    return {"ok": True, "programs": {"train_step[lr]": prog}}


def _toy_chain(**overrides):
    chain = {
        "table": "w", "table_shape": [1 << 22], "table_dtype": "float32",
        "table_bytes": 4 << 22, "occurrences": 32768, "gathers": 1,
        "scatters": 1, "elementwise_table_ops": 31,
        "est_bytes_per_step": 123456,
        "gather_at": ["xflow_tpu/train/step.py", 61],
        "scatter_at": ["xflow_tpu/train/step.py", 61],
    }
    chain.update(overrides)
    return chain


def test_ir_analyze_jaxpr_finds_gather_scatter_chain():
    """XF801's detector on a toy program: big-table gather ->
    elementwise update -> scatter-add is one chain with the table's
    shape/dtype and the op counts."""
    import jax
    import jax.numpy as jnp

    from xflow_tpu.analysis.ir import analyze_jaxpr

    def step(table, idx, g):
        rows = table[idx]             # gather
        upd = rows * 0.5 - g          # elementwise on occurrence side
        table = table * 0.99          # table-wide elementwise sweep
        return table.at[idx].add(upd)  # scatter-add

    sds = jax.ShapeDtypeStruct
    tr = jax.jit(step).trace(
        sds((1 << 20,), jnp.float32), sds((4096,), jnp.int32),
        sds((4096,), jnp.float32))
    facts = analyze_jaxpr(tr.jaxpr.jaxpr, REPO_ROOT,
                          "xflow_tpu/train/step.py",
                          {(1 << 20,): "w"})
    assert facts["gathers"] == 1 and facts["scatters"] == 1
    (chain,) = facts["chains"]
    assert chain["table"] == "w"
    assert chain["table_shape"] == [1 << 20]
    assert chain["occurrences"] == 4096
    assert chain["elementwise_table_ops"] >= 1
    assert chain["est_bytes_per_step"] > 0


def test_ir_analyze_jaxpr_forward_only_gather_is_not_a_chain():
    """predict-style programs gather without scattering: no chain (the
    worklist records UPDATE paths, not forwards)."""
    import jax
    import jax.numpy as jnp

    from xflow_tpu.analysis.ir import analyze_jaxpr

    def fwd(table, idx):
        return table[idx].sum()

    sds = jax.ShapeDtypeStruct
    tr = jax.jit(fwd).trace(sds((1 << 20,), jnp.float32),
                            sds((4096,), jnp.int32))
    facts = analyze_jaxpr(tr.jaxpr.jaxpr, REPO_ROOT,
                          "xflow_tpu/train/step.py", {})
    assert facts["gathers"] == 1 and facts["scatters"] == 0
    assert facts["chains"] == []


def test_ir_analyze_jaxpr_detects_widening_convert():
    """XF802's detector: a big bf16 -> f32 convert is reported with
    shape and element count; small converts are ignored."""
    import jax
    import jax.numpy as jnp

    from xflow_tpu.analysis.ir import analyze_jaxpr

    def f(big, small):
        return (big.astype(jnp.float32).sum()
                + small.astype(jnp.float32).sum())

    sds = jax.ShapeDtypeStruct
    tr = jax.jit(f).trace(sds((1 << 20,), jnp.bfloat16),
                          sds((8,), jnp.bfloat16))
    facts = analyze_jaxpr(tr.jaxpr.jaxpr, REPO_ROOT,
                          "xflow_tpu/train/step.py", {})
    (cv,) = facts["converts"]
    assert cv["from"] == "bfloat16" and cv["to"] == "float32"
    assert cv["elems"] == 1 << 20


def test_ir_analyze_jaxpr_detects_scan_waste_and_clean_scan():
    """XF803's detector: a dead stacked output and an identity carry
    are reported; a scan whose outputs are consumed and whose carry
    changes is clean."""
    import jax
    import jax.numpy as jnp

    from xflow_tpu.analysis.ir import analyze_jaxpr

    sds = jax.ShapeDtypeStruct

    def wasteful(x, y):
        # carry leaf y rides unchanged; stacked ys are never read
        (x, y), _ys = jax.lax.scan(
            lambda c, _: ((c[0] + 1.0, c[1]), c[0]), (x, y), None,
            length=4)
        return x + y

    tr = jax.jit(wasteful).trace(sds((8,), jnp.float32),
                                 sds((8,), jnp.float32))
    facts = analyze_jaxpr(tr.jaxpr.jaxpr, REPO_ROOT,
                          "xflow_tpu/train/step.py", {})
    (sc,) = facts["scans"]
    assert sc["dead_outputs"] == [0]
    assert sc["identity_carries"] == [1]

    def clean(x):
        c, ys = jax.lax.scan(lambda c, _: (c + 1.0, c * 2.0), x, None,
                             length=4)
        return c + ys.sum()

    tr = jax.jit(clean).trace(sds((8,), jnp.float32))
    facts = analyze_jaxpr(tr.jaxpr.jaxpr, REPO_ROOT,
                          "xflow_tpu/train/step.py", {})
    assert facts["scans"] == []


def test_xf801_fires_only_for_unworklisted_chains(tmp_path):
    """A chain recorded in the checked-in worklist is silent; the same
    chain with a changed identity (op count) fires at the scatter's
    anchor."""
    from xflow_tpu.analysis.passes.ir_rules import (
        build_worklist, render_worklist, _xf801,
    )

    facts = _toy_facts(chains=[_toy_chain()])
    root = str(tmp_path)
    (tmp_path / "tools").mkdir()
    (tmp_path / "tools" / "fusion_worklist.json").write_text(
        render_worklist(build_worklist(facts)))
    assert _xf801(facts, root) == []
    # identity change (second scatter appears): XF801 fires
    drifted = _toy_facts(chains=[_toy_chain(scatters=2)])
    (f,) = _xf801(drifted, root)
    assert f.rule == "XF801"
    assert f.path == "xflow_tpu/train/step.py" and f.line == 61
    assert "train_step[lr]" in f.message and "'w'" in f.message


def test_xf801_everything_fires_without_a_worklist(tmp_path):
    from xflow_tpu.analysis.passes.ir_rules import _xf801

    facts = _toy_facts(chains=[_toy_chain()])
    (f,) = _xf801(facts, str(tmp_path))
    assert f.rule == "XF801"


def test_xf802_and_xf803_findings_carry_source_anchors():
    from xflow_tpu.analysis.passes.ir_rules import _xf802, _xf803

    facts = _toy_facts(
        converts=[{"from": "bfloat16", "to": "float32",
                   "shape": [1 << 20], "elems": 1 << 20,
                   "src": ["xflow_tpu/models/fm.py", 42]}],
        scans=[{"dead_outputs": [0], "identity_carries": [],
                "length": 32, "src": ["xflow_tpu/train/step.py", 99]}])
    (f2,) = _xf802(facts)
    assert (f2.rule, f2.path, f2.line) == ("XF802",
                                           "xflow_tpu/models/fm.py", 42)
    assert "bfloat16 -> float32" in f2.message
    (f3,) = _xf803(facts)
    assert (f3.rule, f3.path, f3.line) == ("XF803",
                                           "xflow_tpu/train/step.py", 99)
    assert "no consumer" in f3.message


def test_xf804_donation_mismatch_against_real_ast_records(tmp_path):
    """XF804 compares the AST tier's extracted jit records against the
    lowered signature: a donation the AST cannot see (kwargs splat)
    fires at the jit's line; a matching contract is silent."""
    from xflow_tpu.analysis.passes.ir_rules import _xf804

    root = tmp_path / "tree"
    eng = root / "xflow_tpu" / "train"
    eng.mkdir(parents=True)
    src_literal = (
        "import jax\n\n\ndef build(recorder):\n"
        "    def train_step(state, batch):\n"
        "        return state\n"
        "    jitted = jax.jit(train_step, donate_argnums=(0,))\n"
        "    return recorder.wrap(\"train_step\", jitted)\n"
    )
    (eng / "step.py").write_text(src_literal)
    project = Project.load(str(root))
    facts = _toy_facts()  # lowered donation [0] — matches the literal
    assert _xf804(facts, project) == []
    # hide the donation from the AST tier: mismatch at the jit line
    (eng / "step.py").write_text(src_literal.replace(
        "donate_argnums=(0,)", "**{\"donate_argnums\": (0,)}"))
    findings = _xf804(facts, Project.load(str(root)))
    assert [f.rule for f in findings] == ["XF804"]
    assert findings[0].path == "xflow_tpu/train/step.py"
    assert findings[0].line == 7
    assert "donation" in findings[0].message


def test_xf804_name_matching_handles_fstring_holes():
    from xflow_tpu.analysis.passes.ir_rules import _name_matches

    assert _name_matches("train_step", "train_step")
    assert _name_matches("train_step.fullshard.{mode}",
                         "train_step.fullshard.fm")
    assert not _name_matches("train_step", "predict")
    assert not _name_matches("predict.fullshard.{mode}",
                             "train_step.fullshard.fm")


def test_checked_in_worklist_names_lr_and_fm_chains():
    """ISSUE 15 acceptance: tools/fusion_worklist.json names at least
    the LR and FM gather -> update -> scatter chains, each annotated
    with shape/dtype/bytes."""
    data = json.load(open(os.path.join(REPO_ROOT, "tools",
                                       "fusion_worklist.json")))
    by_table = {}
    for e in data["entries"]:
        by_table.setdefault(e["table"].split("/")[0], []).append(e)
    assert "w" in by_table, "LR chain missing from the worklist"
    assert "wv" in by_table, "FM chain missing from the worklist"
    lr = [e for e in by_table["w"]
          if e["program"].startswith("train_step[lr]")]
    assert lr and lr[0]["table_shape"] == [1 << 22]
    fm = [e for e in by_table["wv"]
          if e["program"] == "train_step[fm.sorted]"]
    assert fm, "the sorted fused-FM chain (the kernel arc's marquee " \
               "target) is missing"
    for e in data["entries"]:
        assert e["table_dtype"] in ("float32", "bfloat16"), e
        assert e["est_bytes_per_step"] > 0, e
        assert e["gathers"] >= 1 and e["scatters"] >= 1, e
        for loc in (e["gather_at"], e["scatter_at"]):
            path, _, line = loc.rpartition(":")
            assert os.path.exists(os.path.join(REPO_ROOT, path)), loc
            assert int(line) >= 1, loc
    # every sorted engine contributes a chain (the per-shard kernel
    # targets the mesh programs lower)
    programs = {e["program"] for e in data["entries"]}
    assert "train_step.replicated[fm]" in programs
    assert "train_step.fullshard.fm[fm]" in programs
    assert "train_step.gspmd[lr]" in programs


def test_worklist_identity_excludes_source_lines():
    """An unrelated edit that only moves a chain's anchor line must not
    fire XF801 (line drift is --check-worklist's job)."""
    from xflow_tpu.analysis.passes.ir_rules import chain_identity

    a = chain_identity("p", _toy_chain())
    b = chain_identity("p", _toy_chain(
        gather_at=["xflow_tpu/train/step.py", 999],
        scatter_at=["xflow_tpu/train/step.py", 999],
        est_bytes_per_step=1))
    assert a == b


def test_run_passes_default_tiers_exclude_ir(tmp_path):
    """Direct run_passes callers (and partial scans) stay AST-only:
    the IR tier runs only when the caller opts in."""
    from xflow_tpu.analysis.core import PASS_REGISTRY

    assert PASS_REGISTRY["ir-tier"][2] == "ir"
    mod = tmp_path / "m.py"
    mod.write_text("x = 1\n")
    import xflow_tpu.analysis.passes.ir_rules as ir_rules

    calls = []
    orig = ir_rules.ir_facts
    ir_rules.ir_facts = lambda root: calls.append(root) or (None, "test")
    try:
        project = Project.load(str(tmp_path), [str(mod)])
        run_passes(project)
        assert calls == []
        run_passes(project, tiers=("ast", "ir"))
        assert calls, "tiers=('ast','ir') must invoke the IR tier"
    finally:
        ir_rules.ir_facts = orig


def test_cli_ir_skip_notice_on_unimportable_tree(tmp_path):
    """A full-tree run over a tree the IR tier cannot import still runs
    every AST rule and prints the skip notice (graceful degradation)."""
    root = tmp_path / "tree"
    (root / "xflow_tpu").mkdir(parents=True)
    (root / "xflow_tpu" / "m.py").write_text(
        "import jax, time\n\n\n@jax.jit\ndef f(x):\n"
        "    return x + time.time()\n")
    r = run_cli("--root", str(root), "--no-baseline")
    assert r.returncode == 1
    assert "XF101" in r.stdout  # AST tier ran
    assert "IR tier skipped" in r.stderr


def test_xf202_fires_in_comprehension_and_not_after(tmp_path):
    """The dataflow comprehension retrofit: a comprehension target in a
    static slot varies per iteration (fires); the same name read after
    the comprehension is the outer binding (quiet)."""
    mod = tmp_path / "m.py"
    mod.write_text(
        "import jax\n\n\ndef f(x, n):\n    return x * n\n\n\n"
        "g = jax.jit(f, static_argnums=(1,))\n\n\n"
        "def comp(x, xs):\n"
        "    return [g(x, k) for k in xs]\n"
    )
    findings = lint(str(mod), rules=["XF202"])
    assert [f.rule for f in findings] == ["XF202"]
    assert findings[0].line == 12
    mod.write_text(
        "import jax\n\n\ndef f(x, n):\n    return x * n\n\n\n"
        "g = jax.jit(f, static_argnums=(1,))\n\n\n"
        "def after(x, xs, k):\n"
        "    ys = [y for y in xs]\n"
        "    return g(x, k)\n"
    )
    assert lint(str(mod), rules=["XF202"]) == []


def test_cli_artifact_gates_green_on_live_tree():
    """--check-contracts and --check-worklist both pass on the
    checked-in artifacts (ISSUE 15 acceptance; the same gates
    tools/smoke_lint.sh runs in CI)."""
    r = run_cli("--check-contracts", "--check-worklist")
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "matches" in r.stdout


# --------------------------------------------------------------- smoke gate


def test_smoke_lint_script(tmp_path):
    """tools/smoke_lint.sh: repo lint green, fixture corpus fires,
    baseline growth/shrink mechanics, seeded-violation drill, ruff
    layer when available — runnable standalone and from CI."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        ["bash", os.path.join(REPO_ROOT, "tools", "smoke_lint.sh"),
         str(tmp_path / "work")],
        capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "smoke_lint: OK" in r.stdout
