import jax.numpy as jnp
import numpy as np

from xflow_tpu.metrics import BucketAUC, auc_logloss, binary_logloss_from_logits, reference_pctr


def test_auc_perfect_and_inverted():
    labels = np.array([1, 1, 0, 0])
    auc, _ = auc_logloss(np.array([0.9, 0.8, 0.2, 0.1]), labels)
    assert auc == 1.0
    auc, _ = auc_logloss(np.array([0.1, 0.2, 0.8, 0.9]), labels)
    assert auc == 0.0


def test_auc_known_value():
    # pairs: (0.8,1),(0.6,0),(0.4,1),(0.2,0) → 3 of 4 pos-neg pairs ranked right
    auc, _ = auc_logloss(np.array([0.8, 0.6, 0.4, 0.2]), np.array([1, 0, 1, 0]))
    assert abs(auc - 0.75) < 1e-9


def test_auc_single_class_is_nan():
    auc, _ = auc_logloss(np.array([0.5, 0.6]), np.array([1, 1]))
    assert np.isnan(auc)


def test_logloss_natural_and_log2():
    p = np.array([0.5, 0.5])
    y = np.array([1, 0])
    _, ll = auc_logloss(p, y)
    assert abs(ll - np.log(0.5)) < 1e-12
    _, ll2 = auc_logloss(p, y, log2=True)
    assert abs(ll2 - (-1.0)) < 1e-12


def test_bucket_auc_approximates_exact():
    rng = np.random.default_rng(0)
    n = 5000
    labels = (rng.random(n) < 0.3).astype(np.float32)
    # informative scores
    scores = np.clip(0.3 * labels + 0.4 * rng.random(n), 0, 1).astype(np.float32)
    exact, _ = auc_logloss(scores, labels)
    st = BucketAUC.init(4096)
    st = st.update(jnp.asarray(scores), jnp.asarray(labels))
    assert abs(st.compute() - exact) < 5e-3


def test_bucket_auc_mergeable():
    rng = np.random.default_rng(1)
    s1, l1 = rng.random(100).astype(np.float32), (rng.random(100) < 0.5).astype(np.float32)
    s2, l2 = rng.random(100).astype(np.float32), (rng.random(100) < 0.5).astype(np.float32)
    joint = BucketAUC.init(512).update(jnp.asarray(np.concatenate([s1, s2])), jnp.asarray(np.concatenate([l1, l2])))
    a = BucketAUC.init(512).update(jnp.asarray(s1), jnp.asarray(l1))
    b = BucketAUC.init(512).update(jnp.asarray(s2), jnp.asarray(l2))
    merged = BucketAUC(pos=a.pos + b.pos, neg=a.neg + b.neg)
    assert abs(joint.compute() - merged.compute()) < 1e-9


def test_reference_pctr_clamps():
    p = np.asarray(reference_pctr(jnp.asarray([-100.0, 0.0, 100.0])))
    assert p[0] == np.float32(1e-6)  # base.h:55-56
    assert abs(p[1] - 0.5) < 1e-7
    assert p[2] == 1.0  # base.h:57-58


def test_bce_matches_naive():
    logits = jnp.asarray([-2.0, 0.0, 3.0])
    labels = jnp.asarray([0.0, 1.0, 1.0])
    got = np.asarray(binary_logloss_from_logits(logits, labels))
    p = 1 / (1 + np.exp(-np.asarray(logits)))
    want = -(np.asarray(labels) * np.log(p) + (1 - np.asarray(labels)) * np.log(1 - p))
    np.testing.assert_allclose(got, want, rtol=1e-4)
