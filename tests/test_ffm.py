"""Field-aware FM (models/ffm.py — BASELINE.json config 5, the model
the reference does not implement; semantic base
`/root/reference/src/model/fm/fm_worker.cc:80-86` extended per-field):
forward math vs a brute-force pair oracle (incl. duplicate fields and
masks), sorted-path == row-major equality across packed/unpacked
storage, full train-step equality, and the learnability gate — FFM
beats a plain FM on field-pair-interaction truth (`truth="ffm"`)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from xflow_tpu.config import Config, override
from xflow_tpu.models import get_model
from xflow_tpu.optim import get_optimizer
from xflow_tpu.train.state import init_state
from xflow_tpu.train.step import make_train_step

NF, K_LAT, LOG2 = 5, 3, 12
S = 1 << LOG2


def ffm_cfg(**kw):
    cfg = override(
        Config(),
        **{
            "model.name": "ffm",
            "model.v_dim": K_LAT,
            "model.num_fields": NF,
            "data.log2_slots": LOG2,
        },
    )
    return override(cfg, **kw) if kw else cfg


def rand_batch(rng, B=32, F=7):
    return {
        "slots": rng.integers(0, S, (B, F)).astype(np.int32),
        "fields": rng.integers(0, NF, (B, F)).astype(np.int32),  # dups happen
        "mask": (rng.random((B, F)) < 0.8).astype(np.float32),
        "labels": (rng.random(B) < 0.4).astype(np.float32),
        "row_mask": np.ones((B,), np.float32),
    }


def oracle_logits(wv, batch):
    """Brute-force Σ_{i<j} ⟨v_{i,f_j}, v_{j,f_i}⟩ + wx over masked
    occurrences — the textbook FFM sum, pairs enumerated explicitly."""
    slots, fields, mask = batch["slots"], batch["fields"], batch["mask"]
    B = slots.shape[0]
    out = np.zeros(B)
    for b in range(B):
        idx = [i for i in range(slots.shape[1]) if mask[b, i] > 0]
        wx = sum(wv[slots[b, i], 0] for i in idx)
        t = 0.0
        for a in range(len(idx)):
            for c in range(a + 1, len(idx)):
                i, j = idx[a], idx[c]
                vi = wv[slots[b, i], 1 + fields[b, j] * K_LAT: 1 + (fields[b, j] + 1) * K_LAT]
                vj = wv[slots[b, j], 1 + fields[b, i] * K_LAT: 1 + (fields[b, i] + 1) * K_LAT]
                t += float(vi @ vj)
        out[b] = wx + t
    return out


def test_forward_matches_pair_oracle():
    rng = np.random.default_rng(0)
    batch = rand_batch(rng)
    wv = rng.normal(0, 1, (S, 1 + NF * K_LAT)).astype(np.float32)
    cfg = ffm_cfg()
    got = np.asarray(
        get_model("ffm").forward(
            {"wv": jnp.asarray(wv)},
            {k: jnp.asarray(v) for k, v in batch.items()},
            cfg,
        )
    )
    np.testing.assert_allclose(got, oracle_logits(wv, batch), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("packed", ["off", "auto"])
def test_sorted_step_matches_row_major(packed):
    """Full train-step equality between the sorted segment path and the
    row-major einsum path, across storage layouts."""
    cfg_s = ffm_cfg(**{"data.sorted_layout": "on", "data.packed_tables": packed,
                       "data.batch_size": 64, "data.max_nnz": 7})
    cfg_r = ffm_cfg(**{"data.sorted_layout": "off", "data.packed_tables": packed,
                       "data.batch_size": 64, "data.max_nnz": 7})
    model, opt = get_model("ffm"), get_optimizer("ftrl")
    rng = np.random.default_rng(1)
    batches = [rand_batch(rng, B=64) for _ in range(3)]

    from xflow_tpu.ops.sorted_table import plan_sorted_batch

    state_s = init_state(model, opt, cfg_s)
    state_r = init_state(model, opt, cfg_r)
    step_s = make_train_step(model, opt, cfg_s)
    step_r = make_train_step(model, opt, cfg_r)
    for b in batches:
        plan = plan_sorted_batch(b["slots"], b["mask"], S, fields=b["fields"])
        sorted_arrays = {
            "labels": jnp.asarray(b["labels"]),
            "row_mask": jnp.asarray(b["row_mask"]),
            "sorted_slots": jnp.asarray(plan.sorted_slots),
            "sorted_row": jnp.asarray(plan.sorted_row),
            "sorted_mask": jnp.asarray(plan.sorted_mask),
            "sorted_fields": jnp.asarray(plan.sorted_fields),
            "win_off": jnp.asarray(plan.win_off),
        }
        state_s, m_s = step_s(state_s, sorted_arrays)
        state_r, m_r = step_r(state_r, {k: jnp.asarray(v) for k, v in b.items()})
        np.testing.assert_allclose(
            float(m_s["loss"]), float(m_r["loss"]), rtol=2e-5
        )
    np.testing.assert_allclose(
        np.asarray(state_s.tables["wv"]).reshape(-1),
        np.asarray(state_r.tables["wv"]).reshape(-1),
        rtol=2e-4, atol=1e-6,
    )


def test_ffm_beats_fm_on_field_interaction_truth(tmp_path, monkeypatch):
    """BASELINE.json config 5's learnability gate: on field-PAIR
    interaction truth (non-separable sign structure — data/synth.py
    `_planted_ffm_truth`), FFM with k=4 beats a plain FM given MORE
    latent budget (k=16). SGD with a real init/lr: under the
    reference-default FTRL, v collapses toward 0 on first touch and
    interaction gradients (∝ v) cannot bootstrap for EITHER model."""
    from xflow_tpu.data.synth import generate_shards
    from xflow_tpu.train.trainer import Trainer

    monkeypatch.chdir(tmp_path)
    nf = 4
    generate_shards(str(tmp_path / "train"), 1, 12000, num_fields=nf,
                    ids_per_field=15, seed=0, noise=0.05, truth="ffm")
    generate_shards(str(tmp_path / "test"), 1, 3000, num_fields=nf,
                    ids_per_field=15, seed=99, noise=0.05, truth="ffm",
                    truth_seed=0)
    base = {
        "data.train_path": str(tmp_path / "train"),
        "data.test_path": str(tmp_path / "test"),
        "data.log2_slots": 13, "data.batch_size": 256, "data.max_nnz": 6,
        "model.num_fields": nf, "train.epochs": 30, "train.pred_dump": False,
        "optim.name": "sgd", "optim.sgd.lr": 0.5, "optim.v_init_sgd": 0.1,
    }
    aucs = {}
    for name, vd in (("ffm", 4), ("fm", 16)):
        cfg = override(Config(), **{**base, "model.name": name, "model.v_dim": vd})
        t = Trainer(cfg)
        t.fit()
        aucs[name], _ = t.evaluate(dump=False)
    assert aucs["ffm"] > 0.8, aucs
    assert aucs["ffm"] > aucs["fm"] + 0.02, aucs


def test_ffm_table_specs_and_init():
    """Fused [S, 1+nf·k] table; w column zero-init even in packed storage."""
    from xflow_tpu.models.base import init_tables
    from xflow_tpu.ops.sorted_table import unpack_table

    cfg = ffm_cfg()
    model = get_model("ffm")
    assert model.table_specs(cfg) == {"wv": (1 + NF * K_LAT,)}
    tables = init_tables(model, cfg, jax.random.PRNGKey(0))
    K = 1 + NF * K_LAT
    logical = np.asarray(unpack_table(tables["wv"], K))
    assert logical.shape == (S, K)
    assert np.all(logical[:, 0] == 0.0)  # w column
    assert np.std(logical[:, 1:]) > 0  # v blocks random


def aligned_batch(rng, B=64, nf=NF):
    """One occurrence per field (columns == fields), random subset
    masked — libffm's natural shape, what the aligned hybrid requires."""
    return {
        "slots": rng.integers(0, S, (B, nf)).astype(np.int32),
        "fields": np.broadcast_to(np.arange(nf, dtype=np.int32), (B, nf)).copy(),
        "mask": (rng.random((B, nf)) < 0.7).astype(np.float32),
        "labels": (rng.random(B) < 0.4).astype(np.float32),
        "row_mask": np.ones((B,), np.float32),
    }


def hybrid_arrays(b, nf=NF):
    from xflow_tpu.models.ffm import ffm_invperm
    from xflow_tpu.ops.sorted_table import plan_sorted_batch

    plan = plan_sorted_batch(b["slots"], b["mask"], S, fields=b["fields"])
    return {
        "labels": jnp.asarray(b["labels"]),
        "row_mask": jnp.asarray(b["row_mask"]),
        "sorted_slots": jnp.asarray(plan.sorted_slots),
        "sorted_row": jnp.asarray(plan.sorted_row),
        "sorted_mask": jnp.asarray(plan.sorted_mask),
        "sorted_fields": jnp.asarray(plan.sorted_fields),
        "win_off": jnp.asarray(plan.win_off),
        "ffm_invperm": jnp.asarray(
            ffm_invperm(plan.sorted_row, plan.sorted_fields,
                        plan.sorted_mask, b["labels"].shape[0], nf)
        ),
    }


@pytest.mark.parametrize("packed", ["off", "auto"])
@pytest.mark.parametrize("fused", ["auto", "off"])
def test_aligned_hybrid_step_matches_row_major(packed, fused):
    """Full train-step equality: the round-5 aligned hybrid (windowed
    gather + placement permutation + MXU selector row side, fused
    scatter+FTRL under `auto`) vs the row-major autodiff oracle path,
    across storage layouts and with/without the fused optimizer."""
    over = {"data.packed_tables": packed, "optim.fused_scatter": fused,
            "data.batch_size": 64, "data.max_nnz": NF}
    cfg_h = ffm_cfg(**{"data.sorted_layout": "on", **over})
    cfg_r = ffm_cfg(**{"data.sorted_layout": "off", **over})
    model, opt = get_model("ffm"), get_optimizer("ftrl")
    rng = np.random.default_rng(7)
    batches = [aligned_batch(rng) for _ in range(3)]
    state_h, state_r = init_state(model, opt, cfg_h), init_state(model, opt, cfg_r)
    step_h, step_r = make_train_step(model, opt, cfg_h), make_train_step(model, opt, cfg_r)
    for b in batches:
        state_h, m_h = step_h(state_h, hybrid_arrays(b))
        state_r, m_r = step_r(state_r, {k: jnp.asarray(v) for k, v in b.items()})
        np.testing.assert_allclose(float(m_h["loss"]), float(m_r["loss"]), rtol=2e-5)
    np.testing.assert_allclose(
        np.asarray(state_h.tables["wv"]).reshape(-1),
        np.asarray(state_r.tables["wv"]).reshape(-1),
        rtol=2e-4, atol=1e-6,
    )
    for part in ("n", "z"):
        np.testing.assert_allclose(
            np.asarray(state_h.opt_state["wv"][part]).reshape(-1),
            np.asarray(state_r.opt_state["wv"][part]).reshape(-1),
            rtol=2e-4, atol=1e-6,
        )


def test_aligned_hybrid_untouched_slots_bitwise_initial():
    """FTRL lazy-init parity through the hybrid: slots no batch touches
    must keep their initial weights BITWISE (the selector-contraction
    VJP is exact at structural zeros — make_ffm_aligned_op docstring)."""
    from xflow_tpu.ops.sorted_table import pack_of, unpack_table

    cfg = ffm_cfg(**{"data.sorted_layout": "on", "data.batch_size": 32,
                     "data.max_nnz": NF})
    model, opt = get_model("ffm"), get_optimizer("ftrl")
    rng = np.random.default_rng(11)
    b = aligned_batch(rng, B=32)
    state0 = init_state(model, opt, cfg)
    K = 1 + NF * K_LAT
    w0 = np.asarray(unpack_table(state0.tables["wv"], K))
    state, _ = make_train_step(model, opt, cfg)(state0, hybrid_arrays(b))
    w1 = np.asarray(unpack_table(state.tables["wv"], K))
    touched = np.zeros(S, bool)
    touched[np.unique(b["slots"][b["mask"] > 0])] = True
    assert (w1[~touched] == w0[~touched]).all(), "untouched slots moved"
    assert not np.array_equal(w1[touched], w0[touched])


def test_trainer_routes_ffm_sorted_and_falls_back_on_dup(tmp_path):
    """Trainer auto: FFM now takes the sorted hybrid; a duplicate-field
    batch runs the row-major fallback in the same run; sorted_layout=on
    rejects duplicate-field batches with the clear error."""
    from xflow_tpu.data.schema import SparseBatch
    from xflow_tpu.train.trainer import Trainer

    cfg = ffm_cfg(**{"data.batch_size": 16, "data.max_nnz": NF,
                     "train.metrics_path": str(tmp_path / "m.jsonl")})
    t = Trainer(cfg)
    assert t._sorted, "FFM auto should select the sorted hybrid now"
    rng = np.random.default_rng(3)
    b = aligned_batch(rng, B=16)
    sb = SparseBatch(slots=b["slots"], fields=b["fields"], mask=b["mask"],
                     labels=b["labels"], row_mask=b["row_mask"])
    arrays = t._batch_arrays(sb)
    assert "ffm_invperm" in arrays and "sorted_slots" in arrays
    dup = dict(b)
    dup["fields"] = dup["fields"].copy()
    dup["fields"][:, 1] = 0  # field 0 twice
    dup["mask"] = np.ones_like(dup["mask"])
    sbd = SparseBatch(slots=dup["slots"], fields=dup["fields"], mask=dup["mask"],
                      labels=dup["labels"], row_mask=dup["row_mask"])
    arrays_dup = t._batch_arrays(sbd)
    assert "sorted_slots" not in arrays_dup and "slots" in arrays_dup

    t_on = Trainer(override(cfg, **{"data.sorted_layout": "on"}))
    with pytest.raises(ValueError, match="aligned"):
        t_on._batch_arrays(sbd)
