"""Sanitizer runs for the native data plane (SURVEY.md §5 "race
detection / sanitizers": the reference has none; our plan gives the one
concurrent C++ component — the MT parser pool,
native/parser.cc (mutex/condvar/atomics) — TSan and ASan+UBSan runs).

Each case rebuilds parser.cc with `-fsanitize=...` (the flag joins the
build-cache key, data/native.py _build_lib) and exercises the
multi-threaded parser against the sequential one in a SUBPROCESS with
the sanitizer runtime LD_PRELOADed (the host python is uninstrumented,
so the runtime must be loaded first) and halt_on_error set: any data
race / heap error / UB exits nonzero and fails the test. Auto-skips
when the toolchain lacks the runtime libraries.
"""

import os
import subprocess
import sys
import textwrap

import pytest

from xflow_tpu.data.synth import generate_shards

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _runtime_for(sanitize: str):
    lib = "libtsan.so" if sanitize.startswith("thread") else "libasan.so"
    try:
        out = subprocess.run(
            ["gcc", f"-print-file-name={lib}"], capture_output=True, text=True
        ).stdout.strip()
    except FileNotFoundError:
        return None
    return out if out and os.path.isabs(out) and os.path.exists(out) else None


DRIVER = textwrap.dedent("""
    import dataclasses, sys
    import numpy as np
    from xflow_tpu.config import DataConfig
    from xflow_tpu.data.native import native_batch_iterator, native_count_rows
    path = sys.argv[1]
    seq = dataclasses.replace(
        DataConfig(log2_slots=16, max_nnz=10),
        parser_threads=1, block_bytes=4096,
    )
    mt = dataclasses.replace(seq, parser_threads=4)
    a = list(native_batch_iterator(path, seq, 64))
    b = list(native_batch_iterator(path, mt, 64))
    assert len(a) == len(b) and len(a) > 0, (len(a), len(b))
    for i, (x, y) in enumerate(zip(a, b)):
        # plain elementwise compares, NOT np.testing: lazily importing
        # numpy.testing inside a TSan-preloaded process deadlocks on
        # some kernels (observed on 4.4 — zero CPU until timeout)
        for field in ("slots", "fields", "mask", "labels"):
            xa, ya = getattr(x, field), getattr(y, field)
            assert (xa == ya).all(), (i, field)
    assert native_count_rows(path, 4096) == sum(
        int(x.row_mask.sum()) for x in a
    )
    print("SANITIZED_PARITY_OK", len(a))
""")


@pytest.mark.parametrize("sanitize", ["thread", "address,undefined"])
def test_mt_parser_under_sanitizer(tmp_path, sanitize):
    runtime = _runtime_for(sanitize)
    if runtime is None:
        pytest.skip(f"no sanitizer runtime for -fsanitize={sanitize}")
    generate_shards(str(tmp_path / "train"), 1, 700, num_fields=7,
                    ids_per_field=40, seed=3)
    driver = tmp_path / "driver.py"
    driver.write_text(DRIVER)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["XFLOW_NATIVE_SANITIZE"] = sanitize
    env["XFLOW_NATIVE_CACHE"] = str(tmp_path / "build")
    # pre-build the sanitized .so WITHOUT the preload: the driver would
    # otherwise spawn g++ with the sanitizer runtime LD_PRELOADed into
    # it, which deadlocks outright on some kernels (observed on 4.4:
    # zero CPU until the timeout). The cache key includes the sanitize
    # flag, so the preloaded driver below picks this build up as-is.
    build = subprocess.run(
        [sys.executable, "-c",
         "from xflow_tpu.data.native import _build_lib; print(_build_lib())"],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert build.returncode == 0, f"sanitized build failed:\n{build.stderr}"
    env["LD_PRELOAD"] = runtime
    # leak checking would flag the PYTHON interpreter's own allocations;
    # the parser's handles are close()d explicitly, which IS exercised
    env["ASAN_OPTIONS"] = "detect_leaks=0:halt_on_error=1:exitcode=66"
    env["TSAN_OPTIONS"] = "halt_on_error=1:exitcode=66"
    r = subprocess.run(
        [sys.executable, str(driver), str(tmp_path / "train-00000")],
        capture_output=True, text=True, env=env, timeout=600,
    )
    if r.returncode != 0 and "cannot be preloaded" in (r.stderr or ""):
        pytest.skip(f"sanitizer runtime not preloadable: {runtime}")
    assert r.returncode == 0, (
        f"-fsanitize={sanitize} run failed "
        f"(rc={r.returncode})\nstdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    )
    assert "SANITIZED_PARITY_OK" in r.stdout, r.stdout
