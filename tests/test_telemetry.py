"""Telemetry subsystem tests (xflow_tpu/telemetry.py, jsonl.py,
tools/metrics_report.py, tools/smoke_telemetry.sh): registry semantics,
StepTimer decomposition, trace windows, record stamping, the
truncation-tolerant reader, and the report tool's summary/check paths.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from xflow_tpu.config import Config, override
from xflow_tpu.data.synth import generate_shards
from xflow_tpu.jsonl import JsonlAppender, read_jsonl, read_jsonl_counted
from xflow_tpu.telemetry import (
    Registry,
    StepTimer,
    TraceWindow,
    default_registry,
    resolve_run_id,
)
from xflow_tpu.train.trainer import Trainer

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------------ registry


def test_counter_semantics():
    r = Registry()
    c = r.counter("n")
    c.inc()
    c.inc(4)
    assert c.value == 5
    assert r.counter("n") is c  # create-or-get
    with pytest.raises(ValueError):
        c.inc(-1)  # counters are monotone


def test_gauge_semantics():
    r = Registry()
    g = r.gauge("depth")
    g.set(3)
    g.set(1.5)
    assert g.value == 1.5


def test_timer_window_percentiles():
    r = Registry()
    t = r.timer("lat")
    for ms in (1, 2, 3, 4, 100):
        t.observe(ms / 1e3)
    assert t.count == 5
    assert t.total_s == pytest.approx(0.110)
    assert t.percentile(50) == pytest.approx(0.003)
    assert t.percentile(99) == pytest.approx(0.100, rel=0.05)
    window = t.window_reset()
    assert len(window) == 5
    # window cleared, totals survive
    assert np.isnan(t.percentile(50))
    assert t.count == 5
    with t.timing():
        time.sleep(0.01)
    assert t.count == 6 and t.percentile(50) >= 0.01


def test_registry_kind_clash_and_snapshot():
    r = Registry()
    r.counter("x").inc(2)
    r.gauge("y").set(7)
    r.timer("z").observe(0.5)
    with pytest.raises(TypeError):
        r.gauge("x")
    snap = r.snapshot()
    assert snap["x"] == 2 and snap["y"] == 7
    assert snap["z.count"] == 1 and snap["z.total_s"] == pytest.approx(0.5)
    r.reset()
    assert r.snapshot() == {}


def test_default_registry_is_shared():
    assert default_registry() is default_registry()


# ----------------------------------------------------------------- StepTimer


def test_step_timer_decomposition_synthetic():
    """30 synthetic steps with known host-side sleeps: every field
    present, steps counted, per-step sum components sane, and the
    step-time total telescopes to the elapsed wall time."""
    st = StepTimer(registry=Registry())

    def feed():
        for i in range(30):
            time.sleep(0.002)  # data wait, inside next()
            yield i

    t0 = time.perf_counter()
    for _ in st.batches(feed()):
        time.sleep(0.001)  # "dispatch"
        st.dispatched({"loss": np.float32(0.5)}, rows=64)
    st.flush()
    elapsed = time.perf_counter() - t0
    assert st.steps == 30
    assert st.rows == 30 * 64
    rec = st.window_record()
    for key in ("steps_per_s", "rows_per_s", "step_time_p50_ms",
                "step_time_p99_ms", "data_wait_ms", "dispatch_ms", "device_ms"):
        assert key in rec, key
    assert rec["data_wait_ms"] >= 2.0  # the sleep inside next()
    assert rec["dispatch_ms"] >= 1.0  # the sleep before dispatched()
    assert rec["step_time_p99_ms"] >= rec["step_time_p50_ms"] > 0
    # completion intervals telescope: their sum is the run's elapsed time
    assert st.steps / max(rec["steps_per_s"], 1e-9) == pytest.approx(
        elapsed, rel=0.25
    )
    # window consumed
    assert st.window_record() == {}


def test_step_timer_sum_matches_elapsed():
    st = StepTimer(registry=Registry())
    reg = st._reg
    t0 = time.perf_counter()
    for _ in st.batches(iter(range(10))):
        time.sleep(0.003)
        st.dispatched({"loss": 0.0}, rows=1)
    st.flush()
    elapsed = time.perf_counter() - t0
    assert reg.timer("step.time").count == 10
    assert reg.timer("step.time").total_s == pytest.approx(elapsed, rel=0.2)


def test_step_timer_closes_abandoned_iterator():
    closed = {}

    def feed():
        try:
            while True:
                yield 0
        finally:
            closed["yes"] = True

    st = StepTimer(registry=Registry())
    for i, _ in enumerate(st.batches(feed())):
        st.dispatched({}, rows=1)
        if i == 2:
            break
    import gc

    gc.collect()
    assert closed.get("yes"), "abandoned inner iterator was not closed"


# --------------------------------------------------------------- TraceWindow


class FakeProfiler:
    def __init__(self):
        self.events = []

    def start_trace(self, d):
        self.events.append(("start", d))

    def stop_trace(self):
        self.events.append(("stop", None))


def test_trace_window_respects_step_range():
    prof = FakeProfiler()
    tw = TraceWindow("dir", start_step=5, num_steps=3, profiler=prof)
    tw.maybe_start_run()
    assert prof.events == []  # window mode: nothing pre-loop
    for step in range(1, 13):
        tw.before_step(step)
        if step < 5:
            assert prof.events == [], f"started early at step {step}"
    tw.close()
    assert prof.events == [("start", "dir"), ("stop", None)]
    # stop fired when step 8 dispatched (5,6,7 traced), not at close
    tw2 = TraceWindow("dir", 5, 3, profiler=FakeProfiler())
    for step in range(1, 8):
        tw2.before_step(step)
    assert tw2._running  # step 8 never dispatched
    tw2.close()
    assert not tw2._running


def test_trace_window_whole_run_mode():
    prof = FakeProfiler()
    tw = TraceWindow("dir", start_step=0, profiler=prof)
    tw.maybe_start_run()
    for step in range(1, 5):
        tw.before_step(step)
    tw.close()
    assert prof.events == [("start", "dir"), ("stop", None)]


def test_trace_window_disabled_without_dir():
    tw = TraceWindow("", start_step=5, num_steps=3, profiler=FakeProfiler())
    tw.maybe_start_run()
    tw.before_step(5)
    tw.close()
    assert tw._prof.events == []


# --------------------------------------------------- trainer integration


def _train_cfg(tmp_path, **kw):
    base = {
        "data.train_path": str(tmp_path / "train"),
        "data.log2_slots": 12,
        "data.batch_size": 64,
        "data.max_nnz": 8,
        "model.num_fields": 6,
        "train.epochs": 1,
        "train.log_every": 10,
        "train.pred_dump": False,
    }
    base.update(kw)
    return override(Config(), **base)


@pytest.fixture
def train_data(tmp_path):
    generate_shards(
        str(tmp_path / "train"), 1, 1920, num_fields=6, ids_per_field=40, seed=0
    )
    return tmp_path


def test_trainer_emits_stamped_window_records(train_data, tmp_path, monkeypatch):
    """Acceptance gate: every record carries ts/rank/run_id; log-window
    records carry the full step decomposition; steps monotone; step-time
    totals ≈ the run's elapsed seconds."""
    monkeypatch.chdir(tmp_path)
    mpath = tmp_path / "run" / "metrics_rank0.jsonl"
    cfg = _train_cfg(train_data, **{"train.metrics_path": str(mpath)})
    # the default registry holds PROCESS totals — clear what earlier
    # tests in this pytest process accumulated so counts are exact
    default_registry().reset()
    res = Trainer(cfg).fit()
    assert res.steps == 30
    recs = read_jsonl(str(mpath))
    assert recs
    for r in recs:
        assert "ts" in r and "rank" in r and "run_id" in r
        assert r["rank"] == 0
    assert len({r["run_id"] for r in recs}) == 1
    windows = [r for r in recs if "rows_per_s" in r]
    assert windows, "no window records"
    for w in windows:
        for key in ("rows_per_s", "steps_per_s", "step_time_p50_ms",
                    "step_time_p99_ms", "data_wait_ms", "dispatch_ms",
                    "device_ms"):
            assert key in w, key
        assert w["rows_per_s"] > 0
        assert w["step_time_p99_ms"] >= w["step_time_p50_ms"] > 0
    steps = [r["step"] for r in recs if "step" in r]
    assert steps == sorted(steps)
    # pipeline counters rode along and the step-time totals telescope
    final = next(r for r in recs if r.get("final"))
    counters = final["counters"]
    assert counters["data.batches"] == 30
    assert counters["data.rows"] == 1920
    assert counters["step.time.count"] == 30
    assert counters["step.time.total_s"] == pytest.approx(res.seconds, rel=0.2)


def test_trainer_trace_window_mid_run(train_data, tmp_path, monkeypatch):
    """Programmatic window: profile dir non-empty, and the profiler was
    started/stopped exactly once at the configured steps."""
    monkeypatch.chdir(tmp_path)
    import glob

    import jax

    calls = []
    real_start, real_stop = jax.profiler.start_trace, jax.profiler.stop_trace
    monkeypatch.setattr(
        jax.profiler, "start_trace",
        lambda d: (calls.append("start"), real_start(d))[1],
    )
    monkeypatch.setattr(
        jax.profiler, "stop_trace", lambda: (calls.append("stop"), real_stop())[1]
    )
    cfg = _train_cfg(
        train_data,
        **{
            "train.profile_dir": str(tmp_path / "prof"),
            "train.trace_start_step": 5,
            "train.trace_num_steps": 5,
        },
    )
    Trainer(cfg).fit()
    assert calls == ["start", "stop"]
    traces = glob.glob(str(tmp_path / "prof" / "**" / "*"), recursive=True)
    assert traces, "trace window produced no profiler output"


def test_quarantine_records_are_stamped(tmp_path):
    """Quarantine and metrics streams must be joinable: both stamped
    with ts/rank/run_id by the shared appender."""
    from xflow_tpu.data.pipeline import batch_iterator
    from xflow_tpu.testing.faults import write_malformed_libffm

    shard = tmp_path / "junk-00000"
    info = write_malformed_libffm(str(shard), n_good=30, n_bad=4, seed=1)
    qpath = tmp_path / "quarantine.jsonl"
    cfg = override(
        Config(),
        **{
            "data.batch_size": 16,
            "data.max_bad_rows": 100,
            "data.quarantine_path": str(qpath),
            "data.log2_slots": 12,
            "data.max_nnz": 8,
        },
    ).data
    list(batch_iterator(str(shard), cfg))
    recs = read_jsonl(str(qpath))
    assert len(recs) == info["bad"]
    for r in recs:
        assert "ts" in r and "rank" in r and "run_id" in r
        assert r["source"] == str(shard)
    # same process → same run id as any other sink would stamp
    assert recs[0]["run_id"] == resolve_run_id()


# ------------------------------------------------------- tolerant reader


def test_read_jsonl_skips_truncated_tail(tmp_path, capsys):
    p = tmp_path / "m.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({"step": 1}) + "\n")
        f.write(json.dumps({"step": 2}) + "\n")
        f.write('{"step": 3, "loss": 0.4')  # crash mid-append
    recs, skipped = read_jsonl_counted(str(p))
    assert [r["step"] for r in recs] == [1, 2]
    assert skipped == 1
    assert "skipped 1 unparseable" in capsys.readouterr().err


def test_read_jsonl_skips_mid_file_garbage(tmp_path):
    p = tmp_path / "m.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({"step": 1}) + "\n")
        f.write("not json at all\n")
        f.write('[1, 2]\n')  # parseable but not a record
        f.write(json.dumps({"step": 2}) + "\n")
    recs, skipped = read_jsonl_counted(str(p), warn=False)
    assert [r["step"] for r in recs] == [1, 2]
    assert skipped == 2


def test_appender_stamps_and_reopens(tmp_path):
    p = tmp_path / "a.jsonl"
    a = JsonlAppender(str(p), stamp={"rank": 3, "run_id": "r1"})
    a.append({"x": 1})
    a.close()
    a.append({"x": 2})  # transparent reopen
    a.close()
    recs = read_jsonl(str(p))
    assert [r["x"] for r in recs] == [1, 2]
    assert all(r["rank"] == 3 and r["run_id"] == "r1" and "ts" in r for r in recs)


# -------------------------------------------------------- metrics_report


def _report(args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "metrics_report.py"),
         *args],
        capture_output=True,
        text=True,
        timeout=120,
    )


@pytest.fixture
def run_jsonl(train_data, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    mpath = tmp_path / "run" / "metrics_rank0.jsonl"
    cfg = _train_cfg(train_data, **{"train.metrics_path": str(mpath)})
    Trainer(cfg).fit()
    return mpath


def test_metrics_report_summary_and_check(run_jsonl, tmp_path):
    r = _report([str(run_jsonl.parent), "--check"])
    assert r.returncode == 0, r.stderr
    assert "OK" in r.stdout
    r = _report([str(run_jsonl.parent)])
    assert r.returncode == 0, r.stderr
    lines = r.stdout.strip().splitlines()
    assert lines[0].split() == [
        "run_id", "rank", "gen", "steps", "examples", "elapsed_s", "ex/s",
        "rows/s", "p50_ms", "p99_ms", "wait_ms", "loss", "bad_steps",
        "bad_rows", "auc",
    ]
    row = lines[2].split()
    assert row[1] == "0" and row[2] == "0"  # rank 0, generation 0
    assert row[3] == "30" and row[4] == "1920"


def test_metrics_report_tolerates_truncation(run_jsonl, tmp_path):
    data = run_jsonl.read_bytes()
    trunc = tmp_path / "trunc" / "metrics_rank0.jsonl"
    trunc.parent.mkdir()
    trunc.write_bytes(data[:-30])  # cut inside the final record
    r = _report([str(trunc)])
    assert r.returncode == 0, r.stderr
    assert "damaged line(s) skipped" in r.stdout
    assert "skipped 1 unparseable" in r.stderr
    r = _report([str(trunc), "--check"])
    assert r.returncode == 0, r.stderr  # damage is skipped, schema still OK


def test_quarantine_stream_tolerates_truncation(run_jsonl, tmp_path):
    """Regression (A3 satellite): a run dir holding BOTH a truncated
    metrics stream and a truncated quarantine stream must still
    summarize and --check clean — the SIGTERM/crash tail of either
    stream is a skipped line, never a dead report."""
    from xflow_tpu.data.pipeline import batch_iterator
    from xflow_tpu.testing.faults import truncate_file, write_malformed_libffm

    run = run_jsonl.parent
    shard = tmp_path / "junk-00000"
    write_malformed_libffm(str(shard), n_good=20, n_bad=3, seed=2)
    qpath = run / "quarantine.jsonl"
    cfg = override(
        Config(),
        **{
            "data.batch_size": 16,
            "data.max_bad_rows": 100,
            "data.quarantine_path": str(qpath),
            "data.log2_slots": 12,
            "data.max_nnz": 8,
        },
    ).data
    list(batch_iterator(str(shard), cfg))
    # tear the tails of BOTH streams (the crash-mid-append shape)
    truncate_file(str(qpath), keep_bytes=os.path.getsize(qpath) - 20)
    truncate_file(str(run_jsonl), keep_bytes=os.path.getsize(run_jsonl) - 25)
    recs, skipped = read_jsonl_counted(str(qpath))
    assert recs and skipped == 1
    assert all("ts" in r and "rank" in r and "run_id" in r for r in recs)
    r = _report([str(run), "--check"])
    assert r.returncode == 0, r.stderr
    assert "2 damaged line(s) skipped" in r.stdout
    r = _report([str(run)])
    assert r.returncode == 0, r.stderr


def test_metrics_report_check_accepts_heartbeat_stream(run_jsonl):
    """A heartbeat stream in the run dir is its own (kind-keyed) stream:
    its step sequence must not be merged into the metrics stream's
    monotonicity check, and its shape is validated."""
    hb = run_jsonl.parent / "heartbeat_rank0.jsonl"
    a = JsonlAppender(
        str(hb), stamp={"rank": 0, "run_id": "hbrun", "kind": "heartbeat"}
    )
    a.append({"event": "start", "step": 0})
    for s in (10, 20, 30):
        a.append({"step": s})
    a.append({"event": "final", "step": 30})
    a.close()
    r = _report([str(run_jsonl.parent), "--check"])
    assert r.returncode == 0, r.stderr
    # a heartbeat record that is neither a beat nor an event fails
    a.append({"nonsense": True})
    a.close()
    r = _report([str(run_jsonl.parent), "--check"])
    assert r.returncode != 0
    assert "neither a step heartbeat nor an event" in r.stderr


def test_metrics_report_bench_json(run_jsonl, tmp_path):
    out = tmp_path / "bench.json"
    r = _report([str(run_jsonl), "--bench-json", str(out)])
    assert r.returncode == 0, r.stderr
    rec = json.loads(out.read_text())
    assert rec["metric"] == "telemetry_examples_per_sec"
    assert rec["unit"] == "examples/sec"
    assert rec["value"] > 0
    assert rec["steps"] == 30 and rec["examples"] == 1920 and rec["ranks"] == 1


def test_metrics_report_check_flags_bad_schema(tmp_path):
    bad = tmp_path / "bad.jsonl"
    with open(bad, "w") as f:
        # unstamped record + backwards step
        f.write(json.dumps({"step": 5, "loss": 0.1}) + "\n")
        f.write(
            json.dumps(
                {"ts": 1.0, "rank": 0, "run_id": "r", "step": 3, "loss": 0.1}
            )
            + "\n"
        )
    r = _report([str(bad), "--check"])
    assert r.returncode != 0
    assert "FAIL" in r.stderr


def test_metrics_report_empty_dir(tmp_path):
    r = _report([str(tmp_path)])
    assert r.returncode != 0


# --------------------------------------------------------------- smoke gate


def test_smoke_telemetry_script(tmp_path):
    """tools/smoke_telemetry.sh: 50-step synthetic train + schema gate,
    runnable standalone and from CI."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        ["bash", os.path.join(REPO_ROOT, "tools", "smoke_telemetry.sh"),
         str(tmp_path / "work")],
        capture_output=True,
        text=True,
        timeout=540,
        env=env,
    )
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "metrics_report: OK" in r.stdout
    assert "smoke_telemetry: OK" in r.stdout


# ------------------------------------------------------------ launch wiring


def test_launch_dist_run_dir_dry_run(tmp_path):
    """--run-dir threads per-rank metrics paths and a shared run id into
    every rank's command line (checked via --dry-run: no ssh runs)."""
    from xflow_tpu.launch.cli import main

    hosts = tmp_path / "hosts.txt"
    hosts.write_text("h0\nh1\n")
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = main(
            ["launch-dist", "--hosts", str(hosts), "--dry-run",
             "--run-dir", "/runs/exp1", "--",
             "--train", "/data/train", "--model", "lr"]
        )
    out = buf.getvalue()
    assert rc == 0
    assert "metrics_rank0.jsonl" in out and "metrics_rank1.jsonl" in out
    assert out.count("XFLOW_RUN_ID=") == 2
    # both ranks share the SAME id
    ids = {
        tok.split("=", 1)[1].strip("'\"")
        for line in out.splitlines()
        for tok in line.split()
        if tok.startswith("XFLOW_RUN_ID=")
    }
    assert len(ids) == 1


def test_launch_local_rank_metrics_args(tmp_path):
    from xflow_tpu.launch.local import rank_metrics_args

    assert rank_metrics_args("", 0) == []
    args = rank_metrics_args(str(tmp_path / "run"), 3)
    assert args[0] == "--set"
    assert args[1].endswith("metrics_rank3.jsonl")
