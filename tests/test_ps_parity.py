"""Async-PS parity gate (BASELINE.md config 1).

The round-1 golden test only checked learnability (train-set AUC). This
gates the actual promise: the framework's synchronous SPMD training
reaches the same test AUC (within epsilon) as a faithful NumPy
re-creation of the reference's Pull/compute/Push loop with server-side
FTRL (tests/ps_simulator.py) — the async->sync semantic shift
(SURVEY.md SS7 hard part c) costs no model quality.

Runs on the reference's bundled fixture when mounted, else on the
synthetic generator with the same shape.
"""

import os

import numpy as np
import pytest

from tests.ps_simulator import (
    sim_predict_fm,
    sim_predict_lr,
    sim_train_fm,
    sim_train_lr,
)
from xflow_tpu.config import Config, override
from xflow_tpu.data.libffm import read_examples
from xflow_tpu.data.synth import generate_shards
from xflow_tpu.metrics import auc_logloss
from xflow_tpu.train.trainer import Trainer

LOG2 = 18
EPOCHS = 40
B = 100


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    ref = "/root/reference/data"
    if os.path.isdir(ref):
        return os.path.join(ref, "small_train"), os.path.join(ref, "small_test")
    d = tmp_path_factory.mktemp("psdata")
    generate_shards(str(d / "small_train"), 1, 100, num_fields=18, ids_per_field=500, seed=1)
    generate_shards(
        str(d / "small_test"), 1, 100, num_fields=18, ids_per_field=500, seed=2, truth_seed=1
    )
    return str(d / "small_train"), str(d / "small_test")


def _batches(path):
    ex = read_examples(path + "-00000", LOG2)
    labels = np.asarray([e[0] for e in ex])
    rows = [e[2] for e in ex]
    return [
        (labels[i : i + B], rows[i : i + B]) for i in range(0, len(labels), B)
    ], labels, rows


def _framework_auc(train_prefix, test_prefix, model, extra=None):
    cfg = override(
        Config(),
        **{
            "data.train_path": train_prefix,
            "data.test_path": test_prefix,
            "data.log2_slots": LOG2,
            "data.batch_size": B,
            "data.max_nnz": 40,
            "model.name": model,
            "model.num_fields": 18,
            "train.epochs": EPOCHS,
            "train.pred_dump": False,
            **(extra or {}),
        },
    )
    t = Trainer(cfg)
    t.fit()
    auc, _ = t.evaluate()
    return auc


def test_lr_ftrl_auc_matches_ps_simulator(data):
    train, test = data
    batches, _, _ = _batches(train)
    table = sim_train_lr(batches, EPOCHS)
    _, test_labels, test_rows = _batches(test)
    p = sim_predict_lr(table, test_rows)
    auc_sim, _ = auc_logloss(p, test_labels)

    auc_fw = _framework_auc(train, test, "lr")
    # the reference's 100-row toy fixture tops out near 0.56 test AUC;
    # the gate is the sim-vs-framework GAP (measured 0.0000 on the
    # fixture: LR residual gradients are exact in both)
    assert auc_sim > 0.52, auc_sim
    assert abs(auc_fw - auc_sim) < 0.02, (auc_fw, auc_sim)


def test_fm_ftrl_auc_matches_ps_simulator(data):
    train, test = data
    batches, _, _ = _batches(train)
    wt, vt = sim_train_fm(batches, EPOCHS, k=10, seed=0)
    _, test_labels, test_rows = _batches(test)
    p = sim_predict_fm(wt, vt, test_rows, k=10)
    auc_sim, _ = auc_logloss(p, test_labels)

    # reference-coupled FM form for apples-to-apples (model.fm_standard=False)
    auc_fw = _framework_auc(
        train, test, "fm", {"model.fm_standard": False}
    )
    assert auc_sim > 0.52, auc_sim
    # measured gap 0.014 on the fixture: the simulator uses the
    # reference's hand-written approximate FM gradients, the framework
    # exact jax.grad ones — AUC-level equivalence, not trajectory-level
    assert abs(auc_fw - auc_sim) < 0.05, (auc_fw, auc_sim)
