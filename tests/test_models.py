import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.oracles import (
    fm_forward_oracle,
    fm_forward_reference_coupled_oracle,
    lr_forward_oracle,
    mvm_forward_oracle,
)
from xflow_tpu.config import Config, override
from xflow_tpu.models import get_model
from xflow_tpu.models.base import init_tables

LOG2 = 10  # 1024 slots — tiny for tests
NF = 4


def small_cfg(**kw):
    cfg = override(
        Config(),
        **{"data.log2_slots": LOG2, "model.v_dim": 3, "model.num_fields": NF},
    )
    return override(cfg, **kw) if kw else cfg


def make_batch_arrays(rows_slots, rows_fields, labels, max_nnz=8):
    B = len(labels)
    slots = np.zeros((B, max_nnz), np.int32)
    fields = np.zeros((B, max_nnz), np.int32)
    mask = np.zeros((B, max_nnz), np.float32)
    for i, (ss, ff) in enumerate(zip(rows_slots, rows_fields)):
        slots[i, : len(ss)] = ss
        fields[i, : len(ff)] = ff
        mask[i, : len(ss)] = 1.0
    return {
        "slots": jnp.asarray(slots),
        "fields": jnp.asarray(fields),
        "mask": jnp.asarray(mask),
        "labels": jnp.asarray(np.asarray(labels, np.float32)),
        "row_mask": jnp.ones((B,), jnp.float32),
    }


ROWS_SLOTS = [[1, 5, 9], [2, 5], [7, 7, 3, 1]]  # note duplicate slot in row 2
ROWS_FIELDS = [[0, 1, 2], [0, 3], [1, 1, 2, 0]]
LABELS = [1.0, 0.0, 1.0]


def test_lr_forward_matches_oracle():
    cfg = small_cfg()
    model = get_model("lr")
    rng = np.random.default_rng(0)
    w = rng.normal(size=(1 << LOG2,)).astype(np.float32)
    batch = make_batch_arrays(ROWS_SLOTS, ROWS_FIELDS, LABELS)
    got = model.forward({"w": jnp.asarray(w)}, batch, cfg)
    want = lr_forward_oracle(w, ROWS_SLOTS)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)


@pytest.mark.parametrize("half", [True, False])
def test_fm_forward_matches_oracle(half):
    cfg = small_cfg(**{"model.fm_half": half})
    model = get_model("fm")
    rng = np.random.default_rng(1)
    w = rng.normal(size=(1 << LOG2,)).astype(np.float32)
    v = rng.normal(size=(1 << LOG2, 3)).astype(np.float32) * 0.1
    batch = make_batch_arrays(ROWS_SLOTS, ROWS_FIELDS, LABELS)
    got = model.forward({"w": jnp.asarray(w), "v": jnp.asarray(v)}, batch, cfg)
    want = fm_forward_oracle(w, v, ROWS_SLOTS, half=half)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)


def test_fm_reference_coupled_mode():
    cfg = small_cfg(**{"model.fm_standard": False})
    model = get_model("fm")
    rng = np.random.default_rng(2)
    w = rng.normal(size=(1 << LOG2,)).astype(np.float32)
    v = rng.normal(size=(1 << LOG2, 3)).astype(np.float32) * 0.1
    batch = make_batch_arrays(ROWS_SLOTS, ROWS_FIELDS, LABELS)
    got = model.forward({"w": jnp.asarray(w), "v": jnp.asarray(v)}, batch, cfg)
    want = fm_forward_reference_coupled_oracle(w, v, ROWS_SLOTS)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)


def test_mvm_forward_matches_oracle():
    cfg = small_cfg()
    model = get_model("mvm")
    rng = np.random.default_rng(3)
    v = rng.normal(size=(1 << LOG2, 3)).astype(np.float32) * 0.5
    batch = make_batch_arrays(ROWS_SLOTS, ROWS_FIELDS, LABELS)
    got = model.forward({"v": jnp.asarray(v)}, batch, cfg)
    want = mvm_forward_oracle(v, ROWS_SLOTS, ROWS_FIELDS, NF)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)


def test_mvm_absent_field_is_identity():
    # a row using only field 0 must not be zeroed by absent fields
    cfg = small_cfg()
    model = get_model("mvm")
    v = np.zeros((1 << LOG2, 3), np.float32)
    v[5] = [2.0, 3.0, 4.0]
    batch = make_batch_arrays([[5]], [[0]], [1.0])
    got = np.asarray(model.forward({"v": jnp.asarray(v)}, batch, cfg))
    assert got[0] == pytest.approx(2.0 + 3.0 + 4.0)


def test_init_tables_shapes_and_init():
    # default storage is PACKED [S/8, 8K] (ops/sorted_table.pack_table:
    # logical [S, 11] would be (8,128)-tile-padded to 11.6x its bytes)
    cfg = small_cfg(**{"model.fm_fused": False})
    key = jax.random.PRNGKey(0)
    t_fm = init_tables(get_model("fm"), cfg, key)
    assert t_fm["w"].shape == (1 << LOG2,)  # scalar tables stay 1-D
    assert t_fm["v"].shape == ((1 << LOG2) // 8, 8 * 3)
    assert float(jnp.abs(t_fm["w"]).max()) == 0.0  # w starts at 0 (ftrl.h:27-36)
    assert 0 < float(jnp.abs(t_fm["v"]).mean()) < 0.1  # ~N(0,1)*1e-2 (ftrl.h:117)
    cfg_sgd = small_cfg(**{"optim.name": "sgd", "model.fm_fused": False})
    t_sgd = init_tables(get_model("fm"), cfg_sgd, key)
    np.testing.assert_allclose(np.asarray(t_sgd["v"]), 1e-3)  # sgd.h:69
    # packed_tables=off keeps the logical layout
    cfg_off = small_cfg(**{"model.fm_fused": False, "data.packed_tables": "off"})
    t_off = init_tables(get_model("fm"), cfg_off, key)
    assert t_off["v"].shape == (1 << LOG2, 3)


def test_init_tables_fused_fm():
    from xflow_tpu.ops.sorted_table import unpack_table

    cfg = small_cfg()  # fm_fused defaults True
    t = init_tables(get_model("fm"), cfg, jax.random.PRNGKey(0))
    assert set(t) == {"wv"}
    assert t["wv"].shape == ((1 << LOG2) // 8, 8 * 4)  # packed, K = 1 + v_dim
    logical = np.asarray(unpack_table(t["wv"], 4))
    assert float(np.abs(logical[:, 0]).max()) == 0.0  # w column zero-init
    assert 0 < float(np.abs(logical[:, 1:]).mean()) < 0.1  # v columns random


def test_fm_fused_matches_two_table_layout():
    # the fused [S, 1+k] table must compute identical forwards and, after a
    # train step, identical updated parameters as the two-table layout
    from xflow_tpu.optim import get_optimizer
    from xflow_tpu.train.state import TrainState
    from xflow_tpu.train.step import make_train_step

    cfg_f = small_cfg()
    cfg_u = small_cfg(**{"model.fm_fused": False})
    model = get_model("fm")
    rng = np.random.default_rng(4)
    w = rng.normal(size=(1 << LOG2,)).astype(np.float32) * 0.1
    v = rng.normal(size=(1 << LOG2, 3)).astype(np.float32) * 0.1
    wv = np.concatenate([w[:, None], v], axis=1)
    batch = make_batch_arrays(ROWS_SLOTS, ROWS_FIELDS, LABELS)

    out_u = model.forward({"w": jnp.asarray(w), "v": jnp.asarray(v)}, batch, cfg_u)
    out_f = model.forward({"wv": jnp.asarray(wv)}, batch, cfg_f)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_u), rtol=1e-6)

    opt = get_optimizer("ftrl")
    t_u = {"w": jnp.asarray(w), "v": jnp.asarray(v)}
    t_f = {"wv": jnp.asarray(wv)}
    s_u = TrainState(t_u, opt.init_state(t_u), jnp.zeros((), jnp.int32))
    s_f = TrainState(t_f, opt.init_state(t_f), jnp.zeros((), jnp.int32))
    s_u, m_u = make_train_step(model, opt, cfg_u)(s_u, batch)
    s_f, m_f = make_train_step(model, opt, cfg_f)(s_f, batch)
    assert float(m_u["loss"]) == pytest.approx(float(m_f["loss"]), rel=1e-6)
    np.testing.assert_allclose(
        np.asarray(s_f.tables["wv"][:, 0]), np.asarray(s_u.tables["w"]), rtol=1e-5, atol=1e-7
    )
    np.testing.assert_allclose(
        np.asarray(s_f.tables["wv"][:, 1:]), np.asarray(s_u.tables["v"]), rtol=1e-5, atol=1e-7
    )


def test_padded_row_gives_zero_logit_lr():
    cfg = small_cfg()
    model = get_model("lr")
    w = jnp.ones((1 << LOG2,))
    batch = make_batch_arrays([[1, 2]], [[0, 1]], [1.0], max_nnz=4)
    batch["mask"] = batch["mask"].at[0, :].set(0.0)
    got = model.forward({"w": w}, batch, cfg)
    assert float(got[0]) == 0.0
