"""Elastic-recovery suite (docs/ROBUSTNESS.md "Elastic recovery"):
supervised auto-restart, exact data-pipeline resume, rendezvous
retry/backoff, restart-generation stamping, and the report tool's
multi-generation segmentation.

The acceptance drill — kill at step K + auto-restart consumes the same
record sequence as an uninterrupted run — is proved two ways: the
in-process parity test here (bitwise-equal final tables through a
non-boundary abort), and the end-to-end launch-local SIGKILL drill
(tests/test_launch_local.py::test_launch_local_supervised_auto_restart
for the 2-process path, tools/smoke_elastic.sh for the CI gate).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from xflow_tpu.config import Config, override
from xflow_tpu.data.synth import generate_shards
from xflow_tpu.launch.supervise import backoff_delay, retry_call, supervise
from xflow_tpu.testing.faults import abort_after_step, corrupt_npz_checkpoint
from xflow_tpu.train.checkpoint import (
    committed_steps,
    data_state_path,
    read_data_state,
)
from xflow_tpu.train.trainer import Trainer

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_cfg(tmp_path, **kw):
    base = {
        "data.train_path": str(tmp_path / "train"),
        "data.log2_slots": 12,
        "data.batch_size": 100,
        "data.max_nnz": 8,
        "model.num_fields": 5,
        "train.epochs": 2,
        "train.pred_dump": False,
    }
    base.update(kw)
    return override(Config(), **base)


@pytest.fixture
def dataset(tmp_path):
    generate_shards(
        str(tmp_path / "train"), 1, 600, num_fields=5, ids_per_field=30, seed=0
    )
    return tmp_path


# ----------------------------------------------------- data_state round trip
def test_checkpoint_carries_versioned_data_state(dataset, tmp_path):
    ck = tmp_path / "ck"
    cfg = make_cfg(dataset, **{"train.checkpoint_dir": str(ck),
                               "train.checkpoint_every": 5})
    Trainer(cfg).fit()
    steps = committed_steps(str(ck))
    assert steps == [12, 10, 5]
    # mid-run checkpoint: mid-stream position, not completed — the
    # topology-independent v2 form: global examples + per-SHARD offsets
    ds5 = read_data_state(str(ck), 5)
    assert ds5 == {
        "version": 2, "epoch": 0, "batches": 5, "completed": False,
        "examples": 500, "examples_per_rank": [500],
        "shard_batches": {"0": 5}, "num_shards": 1, "world_size": 1,
        "quarantined_rows": 0,
    }
    # final checkpoint: all epochs consumed, completed
    ds12 = read_data_state(str(ck), 12)
    assert ds12["completed"] and ds12["epoch"] == 2 and ds12["batches"] == 0
    assert ds12["examples"] == 1200
    # the metadata carries version + logical layout + per-array digests
    # (checkpoint v3: topology-elastic, integrity-verified)
    meta = json.load(open(ck / "step_12" / "meta.json"))
    assert meta["version"] == 3 and meta["world_size"] == 1
    assert meta["layout"]["tables/w"] == [4096]
    assert meta["digests"]["tables/w"].startswith("crc32:")


def test_read_data_state_missing_downgrades(dataset, tmp_path, capsys):
    """Satellite: a COMMITTED checkpoint without a data_state file (a
    pre-PR-4 checkpoint) resumes with a fresh stream and a logged
    downgrade — never an error."""
    ck = tmp_path / "ck"
    cfg = make_cfg(dataset, **{"train.checkpoint_dir": str(ck)})
    Trainer(cfg).fit()
    os.remove(data_state_path(str(ck), 12))
    assert read_data_state(str(ck), 12) is None
    assert "no data_state" in capsys.readouterr().err
    # the resume itself still works: model restores, stream starts fresh
    t2 = Trainer(cfg)
    assert t2.maybe_restore() and int(t2.state.step) == 12
    assert t2._consume_resume_position() == (0, {})


def test_read_data_state_truncated_downgrades(dataset, tmp_path, capsys):
    """Satellite: corrupt_ckpt's data_state mode truncates the file;
    the reader downgrades to a fresh stream instead of raising."""
    ck = tmp_path / "ck"
    cfg = make_cfg(dataset, **{"train.checkpoint_dir": str(ck)})
    Trainer(cfg).fit()
    corrupt_npz_checkpoint(str(ck), target="data_state", mode="truncate",
                           keep_frac=0.3)
    assert read_data_state(str(ck), 12) is None
    assert "unreadable" in capsys.readouterr().err


def test_corrupt_ckpt_cli_data_state_target(dataset, tmp_path):
    """The operator drill tool reaches the new path end to end."""
    ck = tmp_path / "ck"
    cfg = make_cfg(dataset, **{"train.checkpoint_dir": str(ck)})
    Trainer(cfg).fit()
    r = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "corrupt_ckpt.py"),
         "--dir", str(ck), "--target", "data_state", "--mode", "truncate"],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout)["corrupted"].endswith("step_12/data_state.json")
    assert read_data_state(str(ck), 12) is None


def test_data_state_walks_back_with_the_restored_step(dataset, tmp_path):
    """A corrupt newest checkpoint walks restore back — and the stream
    position must come from the step that actually restored, never the
    newer (unreadable) one."""
    ck = tmp_path / "ck"
    cfg = make_cfg(dataset, **{"train.checkpoint_dir": str(ck),
                               "train.checkpoint_every": 5})
    Trainer(cfg).fit()
    corrupt_npz_checkpoint(str(ck), step=12, mode="truncate")
    corrupt_npz_checkpoint(str(ck), step=10, mode="truncate")
    t2 = Trainer(cfg)
    assert t2.maybe_restore()
    assert int(t2.state.step) == 5
    assert t2._resume_data_state["batches"] == 5  # step 5's position


# --------------------------------------------------------- exact resume
def test_resume_exact_stream_parity(dataset, tmp_path):
    """THE parity gate: kill at a NON-boundary step (checkpoint at 5,
    abort after 7 — steps 6-7 lost and retrained) + resume consumes the
    same record sequence as an uninterrupted run: final tables and
    optimizer state are bitwise-close and the step counts match.
    Without data_state the resumed run would replay from row 0 and
    train 17 steps instead of 12."""
    cfg_ref = make_cfg(dataset, **{"train.checkpoint_dir": str(tmp_path / "ck_ref")})
    t_ref = Trainer(cfg_ref)
    assert t_ref.fit().steps == 12

    ck = str(tmp_path / "ck")
    cfg = make_cfg(dataset, **{"train.checkpoint_dir": ck,
                               "train.checkpoint_every": 5})
    t1 = Trainer(cfg)
    abort_after_step(t1, 7)
    with pytest.raises(RuntimeError, match="injected abort"):
        t1.fit()
    assert committed_steps(ck) == [5]

    t2 = Trainer(cfg)
    assert t2.maybe_restore() and int(t2.state.step) == 5
    res = t2.fit()
    assert res.steps == 7  # exactly the un-trained suffix (6..12)
    assert int(t2.state.step) == 12
    np.testing.assert_allclose(
        np.asarray(t2.state.tables["w"]), np.asarray(t_ref.state.tables["w"]),
        rtol=0, atol=1e-6,
        err_msg="resumed stream != uninterrupted stream (record-sequence drift)",
    )
    np.testing.assert_allclose(
        np.asarray(t2.state.opt_state["w"]["n"]),
        np.asarray(t_ref.state.opt_state["w"]["n"]),
        rtol=0, atol=1e-6,
    )
    # cumulative accounting: 7 trained-then-lost-then-retrained... no —
    # 5 kept + 2 retrained + 5 fresh: 500 (gen 0's committed view) +
    # 700 consumed by the resumed fit
    ds = read_data_state(ck, 12)
    assert ds["completed"] and ds["examples"] == 1200


def test_resume_mid_later_epoch(dataset, tmp_path):
    """The epoch component matters too: abort inside epoch 1 (step 9 =
    epoch 1, batch 3); resume continues at that exact (epoch, batch)."""
    ck = str(tmp_path / "ck")
    cfg = make_cfg(dataset, **{"train.checkpoint_dir": ck,
                               "train.checkpoint_every": 8})
    t1 = Trainer(cfg)
    abort_after_step(t1, 9)
    with pytest.raises(RuntimeError, match="injected abort"):
        t1.fit()
    assert committed_steps(ck) == [8]
    assert read_data_state(ck, 8) == {
        "version": 2, "epoch": 1, "batches": 2, "completed": False,
        "examples": 800, "examples_per_rank": [800],
        "shard_batches": {"0": 2}, "num_shards": 1, "world_size": 1,
        "quarantined_rows": 0,
    }
    t2 = Trainer(cfg)
    assert t2.maybe_restore()
    res = t2.fit()
    assert res.steps == 4 and int(t2.state.step) == 12


def test_resume_restores_global_example_accounting(dataset):
    """Example accounting is GLOBAL and topology-independent (v2):
    the restored total becomes the base, each rank's local counter
    restarts at 0, and the next checkpoint's `examples` = base + the
    sum of this generation's per-rank counts — exact whatever the rank
    counts on either side. A v1 per-rank-keyed record folds in by
    summation (the satellite downgrade path), and its global
    coordinated offset fans out to every shard (v1 runs consumed their
    shards in lockstep, so the fold is exact)."""
    t = Trainer(make_cfg(dataset), process_index=1)
    t._resume_data_state = {
        "version": 1, "epoch": 0, "batches": 10, "completed": False,
        "examples": 1000, "examples_per_rank": [1000, 900],
    }
    assert t._consume_resume_position() == (0, {0: 10, 1: 10})
    assert t._examples_base == 1900 and t._examples_seen == 0
    assert t._num_shards == 2
    # single-process / legacy data_state: the scalar already is global
    t2 = Trainer(make_cfg(dataset))
    t2._resume_data_state = {
        "version": 1, "epoch": 1, "batches": 2, "completed": False,
        "examples": 800,
    }
    assert t2._consume_resume_position() == (1, {0: 2})
    assert t2._examples_base == 800 and t2._examples_seen == 0
    # v2 record: per-shard offsets pass through verbatim
    t3 = Trainer(make_cfg(dataset))
    t3._resume_data_state = {
        "version": 2, "epoch": 0, "batches": 7, "completed": False,
        "examples": 1400, "examples_per_rank": [700, 700],
        "shard_batches": {"0": 7, "1": 4}, "num_shards": 2,
        "world_size": 2,
    }
    assert t3._consume_resume_position() == (0, {0: 7, 1: 4})
    assert t3._examples_base == 1400 and t3._num_shards == 2


def test_completed_checkpoint_restarts_fresh_pass(dataset, tmp_path):
    """Continuation training (pinned by test_trainer.py): resuming a
    COMPLETED run's checkpoint starts a fresh pass instead of training
    nothing — the `completed` flag is the discriminator."""
    ck = str(tmp_path / "ck")
    cfg = make_cfg(dataset, **{"train.checkpoint_dir": ck})
    Trainer(cfg).fit()
    t2 = Trainer(cfg)
    assert t2.maybe_restore()
    assert t2._consume_resume_position() == (0, {})


def test_skip_batches_fast_forward(dataset):
    """The pipeline seam: skip=N yields exactly the stream's suffix —
    same labels, same order — and the monitor never sees the prefix."""
    from xflow_tpu.data.pipeline import batch_iterator

    cfg = make_cfg(dataset).data
    shard = str(dataset / "train-00000")
    full = [np.asarray(b.labels) for b in batch_iterator(shard, cfg)]
    tail = [np.asarray(b.labels) for b in batch_iterator(shard, cfg, skip=4)]
    assert len(tail) == len(full) - 4
    for a, b in zip(tail, full[4:]):
        np.testing.assert_array_equal(a, b)


# ------------------------------------------------------- supervision loop
def test_supervise_restarts_until_success():
    rcs = iter([3, 2, 0])
    gens, naps = [], []

    def attempt(gen):
        gens.append(gen)
        return next(rcs)

    rc = supervise(attempt, max_restarts=5, restart_backoff=0.5,
                   sleep=naps.append, clock=lambda: 0.0)
    assert rc == 0 and gens == [0, 1, 2]
    assert len(naps) == 2
    # exponential with jitter: delay k in [0.5, 1.0] * base * 2^k
    assert 0.25 <= naps[0] <= 0.5 and 0.5 <= naps[1] <= 1.0


def test_supervise_budget_exhausted_returns_last_rc():
    rc = supervise(lambda gen: 7, max_restarts=2, restart_backoff=0.0,
                   sleep=lambda s: None, clock=lambda: 0.0)
    assert rc == 7


def test_supervise_zero_restarts_is_single_attempt():
    calls = []
    rc = supervise(lambda gen: calls.append(gen) or 9, max_restarts=0)
    assert rc == 9 and calls == [0]


def test_supervise_min_uptime_stops_crash_loops():
    clock = iter([0.0, 0.5])  # attempt "ran" 0.5s < min_uptime 2.0
    calls = []
    rc = supervise(lambda gen: calls.append(gen) or 5, max_restarts=3,
                   min_uptime_s=2.0, sleep=lambda s: None,
                   clock=lambda: next(clock))
    assert rc == 5 and calls == [0]  # config error: no restart burned


def test_backoff_delay_caps_and_jitters():
    class FixedRng:
        def uniform(self, a, b):
            return b  # upper edge

    assert backoff_delay(0, 1.0, rng=FixedRng()) == 1.0
    assert backoff_delay(3, 1.0, rng=FixedRng()) == 8.0
    assert backoff_delay(20, 1.0, cap_s=60.0, rng=FixedRng()) == 60.0
    d = backoff_delay(2, 1.0)
    assert 2.0 <= d <= 4.0


def test_retry_call_retries_then_succeeds():
    attempts, cleanups = [], []

    def flaky():
        attempts.append(1)
        if len(attempts) < 3:
            raise ConnectionError("coordinator not up yet")
        return "joined"

    got = retry_call(flaky, "rendezvous", retries=3, base_s=0.0,
                     cleanup=lambda: cleanups.append(1), sleep=lambda s: None)
    assert got == "joined" and len(attempts) == 3 and len(cleanups) == 2


def test_retry_call_exhausted_raises_last():
    def always():
        raise ConnectionError("still down")

    with pytest.raises(ConnectionError, match="still down"):
        retry_call(always, "rendezvous", retries=2, base_s=0.0,
                   sleep=lambda s: None)


def test_rendezvous_retry_env_parses_defensively(monkeypatch):
    from xflow_tpu.parallel.distributed import _rendezvous_retry_env

    assert _rendezvous_retry_env() == (3, 1.0)
    monkeypatch.setenv("XFLOW_RENDEZVOUS_RETRIES", "5")
    monkeypatch.setenv("XFLOW_RENDEZVOUS_BACKOFF_S", "0.25")
    assert _rendezvous_retry_env() == (5, 0.25)
    monkeypatch.setenv("XFLOW_RENDEZVOUS_RETRIES", "garbage")
    assert _rendezvous_retry_env()[0] == 3


# ------------------------------------------------- generations & watchdog
def test_gen_stamp_in_every_jsonl_record(tmp_path, monkeypatch):
    from xflow_tpu.jsonl import JsonlAppender

    path = tmp_path / "m.jsonl"
    monkeypatch.setenv("XFLOW_RESTART_GEN", "2")
    ap = JsonlAppender(str(path), stamp={"rank": 0, "run_id": "r"})
    ap.append({"step": 1})
    ap.close()
    rec = json.loads(open(path).read())
    assert rec["gen"] == 2 and rec["rank"] == 0


def test_kill_injector_env_gating(monkeypatch):
    from xflow_tpu.testing.faults import kill_step_from_env

    assert kill_step_from_env(0) == 0
    monkeypatch.setenv("XFLOW_FAULT_KILL_STEP", "7")
    assert kill_step_from_env(0) == 7
    monkeypatch.setenv("XFLOW_FAULT_KILL_RANK", "1")
    assert kill_step_from_env(0) == 0 and kill_step_from_env(1) == 7
    # a restarted generation must NOT die again
    monkeypatch.setenv("XFLOW_RESTART_GEN", "1")
    assert kill_step_from_env(1) == 0
    monkeypatch.setenv("XFLOW_FAULT_KILL_GEN", "1")
    assert kill_step_from_env(1) == 7


def test_watchdog_on_dead_policy(tmp_path):
    """The escalation seam: a rank going dead fires the pluggable
    on_dead exactly once per transition, with the status row."""
    from xflow_tpu.launch.watchdog import RunWatchdog

    hb = tmp_path / "heartbeat_rank0.jsonl"
    with open(hb, "w") as f:
        # a STALE beat from the previous generation: the gen-1 watchdog
        # must ignore it (it would otherwise re-fire the dead policy
        # before the relaunched rank's first beat — a teardown loop)
        f.write(json.dumps({"ts": 900.0, "rank": 0, "run_id": "r",
                            "kind": "heartbeat", "gen": 0, "step": 9}) + "\n")
        f.write(json.dumps({"ts": 1000.0, "rank": 0, "run_id": "r",
                            "kind": "heartbeat", "gen": 1, "step": 3}) + "\n")
    fired = []
    wd = RunWatchdog(str(tmp_path), num_ranks=1, dead_after_s=10.0,
                     run_id="r", out=open(os.devnull, "w"),
                     on_dead=fired.append, gen=1)
    try:
        rows = wd.poll_once(now=1005.0)  # fresh (gen-1 beat): ok
        assert fired == [] and rows[0]["step"] == 3  # gen-0 beat ignored
        wd.poll_once(now=1100.0)  # stale: dead -> policy fires once
        wd.poll_once(now=1101.0)  # still dead: no re-fire
    finally:
        wd.stop()
    assert len(fired) == 1
    assert fired[0]["rank"] == 0 and fired[0]["status"] == "dead"
    # the watchdog's own events carry the launcher-provided generation
    events = [json.loads(l) for l in open(tmp_path / "watchdog.jsonl")]
    assert events and all(e["gen"] == 1 for e in events)


def test_watchdog_on_dead_error_does_not_kill_poller(tmp_path):
    from xflow_tpu.launch.watchdog import RunWatchdog

    hb = tmp_path / "heartbeat_rank0.jsonl"
    with open(hb, "w") as f:
        f.write(json.dumps({"ts": 1000.0, "rank": 0, "run_id": "r",
                            "kind": "heartbeat", "gen": 0, "step": 3}) + "\n")

    def boom(row):
        raise RuntimeError("policy bug")

    wd = RunWatchdog(str(tmp_path), num_ranks=1, dead_after_s=10.0,
                     run_id="r", out=open(os.devnull, "w"), on_dead=boom)
    try:
        rows = wd.poll_once(now=1100.0)  # must not raise
    finally:
        wd.stop()
    assert rows[0]["status"] == "dead"


# ------------------------------------------------- report-tool segmentation
def _rec(run_id, rank, gen, step, ts):
    return {"ts": ts, "rank": rank, "run_id": run_id, "gen": gen,
            "step": step, "loss": 0.5, "examples": step * 10,
            "elapsed_s": float(step), "steps_per_s": 1.0, "rows_per_s": 10.0,
            "step_time_p50_ms": 1.0, "step_time_p99_ms": 2.0,
            "data_wait_ms": 0.1, "dispatch_ms": 0.1, "device_ms": 0.8}


def test_check_accepts_multi_generation_stream(tmp_path):
    """A supervised restart resets the step counter inside one run_id;
    keyed on gen the stream passes --check, stripped of gen it would
    trip the step-monotonicity gate — both directions pinned."""
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    import metrics_report

    path = tmp_path / "metrics_rank0.jsonl"
    recs = [_rec("r", 0, 0, 5, 1.0), _rec("r", 0, 0, 10, 2.0),
            _rec("r", 0, 1, 2, 3.0), _rec("r", 0, 1, 4, 4.0)]
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    streams, _ = metrics_report.load_streams([str(path)])
    assert set(streams) == {("r", 0, "metrics", 0), ("r", 0, "metrics", 1)}
    assert metrics_report.check_streams(streams, [str(path)]) == []

    # negative control: the same records WITHOUT the gen stamp collapse
    # into one stream whose steps go backwards
    flat = tmp_path / "flat.jsonl"
    with open(flat, "w") as f:
        for r in recs:
            r = dict(r)
            r.pop("gen")
            f.write(json.dumps(r) + "\n")
    streams2, _ = metrics_report.load_streams([str(flat)])
    problems = metrics_report.check_streams(streams2, [str(flat)])
    assert any("step went backwards" in p for p in problems)


def test_bench_record_sums_generations(tmp_path):
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    import metrics_report

    path = tmp_path / "metrics_rank0.jsonl"
    g0 = _rec("r", 0, 0, 30, 1.0)
    g0["eval_auc"], g0["eval_logloss"] = 0.80, 0.5
    g1 = _rec("r", 0, 1, 20, 2.0)
    g1["eval_auc"], g1["eval_logloss"] = 0.74, 0.6
    with open(path, "w") as f:
        for r in [g0, g1]:
            f.write(json.dumps(r) + "\n")
    streams, _ = metrics_report.load_streams([str(path)])
    rec = metrics_report.bench_record(streams)
    assert rec["steps"] == 50  # 30 (gen 0) + 20 (gen 1)
    assert rec["examples"] == 500 and rec["generations"] == 2
    assert rec["elapsed_s"] == 50.0  # per-gen elapsed sums
    # quality = the NEWEST generation's model (what actually ships) —
    # a superseded gen-0 AUC must not satisfy --regress
    assert rec["auc"] == 0.74


def test_fold_heartbeats_tolerates_damaged_gen():
    """One record with a junk gen (string, NaN) must be skipped, not
    raise and blind every later watchdog scan."""
    from xflow_tpu.launch.watchdog import fold_heartbeats

    recs = [
        {"ts": 1.0, "rank": 0, "run_id": "r", "gen": "x", "step": 1},
        {"ts": 2.0, "rank": 0, "run_id": "r", "gen": float("nan"), "step": 2},
        {"ts": 3.0, "rank": 0, "run_id": "r", "gen": 1, "step": 3},
    ]
    beats = fold_heartbeats(recs, run_id="r", gen=1)
    assert beats == {0: {"step": 3, "ts": 3.0, "event": None, "gen": 1}}


def test_heartbeat_brackets_eval_and_checkpoint(dataset, tmp_path):
    """A quiet eval/checkpoint phase must not age into a dead verdict
    (under supervision that verdict is a TEARDOWN): the trainer
    brackets both with heartbeat events."""
    hb = tmp_path / "heartbeat_rank0.jsonl"
    generate_shards(str(dataset / "test"), 1, 100, num_fields=5,
                    ids_per_field=30, seed=7, truth_seed=0)
    cfg = make_cfg(dataset, **{
        "train.heartbeat_path": str(hb),
        "train.checkpoint_dir": str(tmp_path / "ck"),
        "train.checkpoint_every": 5,
        "train.eval_every": 1,
        "data.test_path": str(dataset / "test"),
    })
    Trainer(cfg).fit()
    events = [r.get("event") for r in map(json.loads, open(hb))]
    assert "checkpoint" in events and "eval" in events and "final" in events


# ----------------------------------------------------------- CI smoke gate
def test_smoke_elastic_script(tmp_path):
    """The elastic-recovery CI gate end to end: clean supervised run +
    bench datapoint + kill-and-recover drill with exact accounting
    (tools/smoke_elastic.sh; the acceptance criterion's drill)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        ["bash", os.path.join(REPO_ROOT, "tools", "smoke_elastic.sh"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=570, env=env,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "smoke_elastic: OK" in r.stdout
    assert "kill drill accounting OK" in r.stdout
    # the bench datapoint landed in the workdir (never the repo root
    # from pytest), carrying the clean run's steady-state throughput
    bench = json.load(open(tmp_path / "BENCH_r07.json"))
    assert bench["metric"] == "telemetry_examples_per_sec"
    assert bench["steps"] == 50 and bench["value"] > 0
