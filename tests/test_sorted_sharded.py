"""Sharded sorted-window FM step (parallel/sorted_sharded.py): equality
vs the single-device sorted path on the 8-virtual-CPU-device mesh, and
sharding-placement invariants."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from xflow_tpu.config import Config, override
from xflow_tpu.models import get_model
from xflow_tpu.ops.sorted_table import plan_sorted_batch, plan_sorted_stacked
from xflow_tpu.optim import get_optimizer
from xflow_tpu.parallel.mesh import make_mesh
from xflow_tpu.parallel.sorted_sharded import (
    make_sorted_sharded_train_step,
    shard_sorted_state,
    validate_sorted_sharded,
)
from xflow_tpu.train.state import TrainState, init_state
from xflow_tpu.train.step import make_train_step


def _cfg(d, t, **kw):
    return override(
        Config(),
        **{
            "model.name": "fm",
            "data.log2_slots": 14,  # 16384 slots = 8 windows
            "data.max_nnz": 8,
            "data.batch_size": 64,
            "mesh.data": d,
            "mesh.table": t,
            **kw,
        },
    )


def _batch(cfg, rng, B):
    S, F = cfg.num_slots, cfg.data.max_nnz
    slots = rng.integers(0, S, (B, F)).astype(np.int32)
    mask = (rng.random((B, F)) < 0.7).astype(np.float32)
    labels = (rng.random(B) < 0.5).astype(np.float32)
    return slots, mask, labels



def _plan_batch(plan, labels, B):
    """Step-input dict from a SortedPlan (flat or stacked)."""
    return {
        "labels": jnp.asarray(labels),
        "row_mask": jnp.ones((B,), jnp.float32),
        "sorted_slots": jnp.asarray(plan.sorted_slots),
        "sorted_row": jnp.asarray(plan.sorted_row),
        "sorted_mask": jnp.asarray(plan.sorted_mask),
        "win_off": jnp.asarray(plan.win_off),
    }


@pytest.mark.parametrize("d,t", [(2, 4), (4, 2), (8, 1), (1, 8)])
def test_sharded_sorted_step_matches_single_device(d, t):
    cfg = _cfg(d, t)
    mesh = make_mesh(cfg, devices=jax.devices()[:8])
    rng = np.random.default_rng(31)
    B = cfg.data.batch_size
    slots, mask, labels = _batch(cfg, rng, B)
    model, opt = get_model("fm"), get_optimizer("ftrl")

    # single-device sorted reference
    state0 = init_state(model, opt, cfg)
    wv0 = np.asarray(state0.tables["wv"])
    plan1 = plan_sorted_batch(slots, mask, cfg.num_slots)
    ref_batch = _plan_batch(plan1, labels, B)
    step1 = make_train_step(model, opt, cfg)
    s_ref, m_ref = step1(
        TrainState({"wv": jnp.asarray(wv0)},
                   opt.init_state({"wv": jnp.asarray(wv0)}),
                   jnp.zeros((), jnp.int32)),
        ref_batch,
    )

    # sharded sorted step: per-data-shard plans, table sharded over 'table'
    plans = plan_sorted_stacked(slots, mask, cfg.num_slots, num_sub=d, always_stack=True)
    batch = _plan_batch(plans, labels, B)
    state = shard_sorted_state(
        TrainState({"wv": jnp.asarray(wv0)},
                   opt.init_state({"wv": jnp.asarray(wv0)}),
                   jnp.zeros((), jnp.int32)),
        mesh,
    )
    step = make_sorted_sharded_train_step(opt, cfg, mesh)
    s_sh, m_sh = step(state, batch)

    assert float(m_sh["loss"]) == pytest.approx(float(m_ref["loss"]), rel=1e-5)
    assert float(m_sh["rows"]) == float(m_ref["rows"])
    # table shards reassemble to the single-device result
    np.testing.assert_allclose(
        np.asarray(s_sh.tables["wv"]), np.asarray(s_ref.tables["wv"]),
        rtol=1e-4, atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(s_sh.opt_state["wv"]["n"]), np.asarray(s_ref.opt_state["wv"]["n"]),
        rtol=1e-4, atol=1e-7,
    )
    # placement: the wv table is split on slot over 'table' only
    # (stored rows: packed layout holds 8 slots per row)
    shard_rows = {sh.data.shape[0] for sh in s_sh.tables["wv"].addressable_shards}
    assert shard_rows == {s_sh.tables["wv"].shape[0] // t}


def test_sharded_sorted_multi_step_trajectory():
    d, t = 2, 4
    cfg = _cfg(d, t)
    mesh = make_mesh(cfg, devices=jax.devices()[:8])
    rng = np.random.default_rng(7)
    B = cfg.data.batch_size
    model, opt = get_model("fm"), get_optimizer("ftrl")
    state0 = init_state(model, opt, cfg)
    wv0 = np.asarray(state0.tables["wv"])

    step1 = make_train_step(model, opt, cfg)
    s_ref = TrainState({"wv": jnp.asarray(wv0)},
                       opt.init_state({"wv": jnp.asarray(wv0)}),
                       jnp.zeros((), jnp.int32))
    step_sh = make_sorted_sharded_train_step(opt, cfg, mesh)
    s_sh = shard_sorted_state(
        TrainState({"wv": jnp.asarray(wv0)},
                   opt.init_state({"wv": jnp.asarray(wv0)}),
                   jnp.zeros((), jnp.int32)),
        mesh,
    )
    for i in range(3):
        slots, mask, labels = _batch(cfg, rng, B)
        p1 = plan_sorted_batch(slots, mask, cfg.num_slots)
        s_ref, m_ref = step1(
            s_ref,
            _plan_batch(p1, labels, B),
        )
        pd = plan_sorted_stacked(slots, mask, cfg.num_slots, num_sub=d)
        s_sh, m_sh = step_sh(
            s_sh,
            _plan_batch(pd, labels, B),
        )
        assert float(m_sh["loss"]) == pytest.approx(float(m_ref["loss"]), rel=1e-5), i
    np.testing.assert_allclose(
        np.asarray(s_sh.tables["wv"]), np.asarray(s_ref.tables["wv"]),
        rtol=1e-4, atol=1e-6,
    )


def test_validate_sorted_sharded_rejects_bad_configs():
    mesh = make_mesh(_cfg(2, 4), devices=jax.devices()[:8])
    with pytest.raises(ValueError, match="divisible by table_axis"):
        validate_sorted_sharded(_cfg(2, 4, **{"data.log2_slots": 12}), mesh)
    with pytest.raises(ValueError, match="fused FM only"):
        validate_sorted_sharded(_cfg(2, 4, **{"model.name": "lr"}), mesh)
    with pytest.raises(ValueError, match="not divisible by"):
        validate_sorted_sharded(_cfg(2, 4, **{"data.batch_size": 63}), mesh)
    with pytest.raises(ValueError, match="conflicts with the mesh sorted path"):
        validate_sorted_sharded(_cfg(2, 4, **{"data.sorted_sub_batches": 8}), mesh)


def test_trainer_mesh_sorted_matches_gspmd(tmp_path):
    """Trainer wiring: fused FM on a (2,4) mesh with sorted_layout on vs
    off (GSPMD row-major) — identical final tables and AUC."""
    from xflow_tpu.data.synth import generate_shards
    from xflow_tpu.train.trainer import Trainer

    generate_shards(str(tmp_path / "train"), 1, 400, num_fields=5, ids_per_field=60, seed=13)

    def run(sorted_layout):
        cfg = override(
            Config(),
            **{
                "data.train_path": str(tmp_path / "train"),
                "data.test_path": str(tmp_path / "train"),
                "data.log2_slots": 14,
                "data.batch_size": 64,
                "data.max_nnz": 8,
                "data.sorted_layout": sorted_layout,
                "model.name": "fm",
                "model.num_fields": 5,
                "mesh.data": 2,
                "mesh.table": 4,
                "train.epochs": 2,
                "train.pred_dump": False,
            },
        )
        mesh = make_mesh(cfg, devices=jax.devices()[:8])
        tr = Trainer(cfg, mesh=mesh)
        assert tr._sorted == (sorted_layout == "on")
        assert tr._sorted_sharded == (sorted_layout == "on")
        tr.fit()
        return tr

    t_on, t_off = run("on"), run("off")
    np.testing.assert_allclose(
        np.asarray(t_on.state.tables["wv"]), np.asarray(t_off.state.tables["wv"]),
        rtol=1e-4, atol=1e-6,
    )
    auc_on, _ = t_on.evaluate()
    auc_off, _ = t_off.evaluate()
    assert auc_on == pytest.approx(auc_off, abs=1e-6)


def test_sorted_sharded_checkpoint_roundtrip(tmp_path):
    """The table-axis-only sharded state (P('table', None)) survives an
    npz save/restore with sharding and values intact."""
    from xflow_tpu.train import checkpoint as ckpt

    cfg = _cfg(2, 4)
    mesh = make_mesh(cfg, devices=jax.devices()[:8])
    model, opt = get_model("fm"), get_optimizer("ftrl")
    state = shard_sorted_state(init_state(model, opt, cfg), mesh)
    rng = np.random.default_rng(3)
    slots, mask, labels = _batch(cfg, rng, cfg.data.batch_size)
    plans = plan_sorted_stacked(slots, mask, cfg.num_slots, num_sub=2, always_stack=True)
    step = make_sorted_sharded_train_step(opt, cfg, mesh)
    state, _ = step(state, _plan_batch(plans, labels, cfg.data.batch_size))
    ckpt.save(str(tmp_path), state)
    like = shard_sorted_state(init_state(model, opt, cfg), mesh)
    restored = ckpt.restore(str(tmp_path), like)
    np.testing.assert_array_equal(
        np.asarray(restored.tables["wv"]), np.asarray(state.tables["wv"])
    )
    np.testing.assert_array_equal(
        np.asarray(restored.opt_state["wv"]["z"]), np.asarray(state.opt_state["wv"]["z"])
    )
    assert restored.tables["wv"].sharding == state.tables["wv"].sharding
    assert int(restored.step) == int(state.step) == 1
