"""Async tiered checkpointing suite (docs/ROBUSTNESS.md "Async tiered
checkpointing"): the background save pipeline (`train.ckpt_async`), the
tier-2 replica mirror (`train.ckpt_replica_dir`), the tiered restore
walk, the disk-fault injectors, the `kind="ckpt"` telemetry gates, and
the synchronous-mode artifact-identity pin.

The acceptance drills — kill mid-async-save resumes with exact example
accounting; a digest-poisoned primary restores from the replica tier in
the trainer AND the serve watcher — run here in-process/subprocess and
end-to-end via tools/smoke_durable.sh (test_smoke_durable_script)."""

import json
import os
import shutil
import subprocess
import sys

import jax
import numpy as np
import pytest

from xflow_tpu.config import Config, override
from xflow_tpu.data.synth import generate_shards
from xflow_tpu.testing.faults import (
    ckpt_write_fault,
    corrupt_npz_checkpoint,
    corrupt_orbax_checkpoint,
)
from xflow_tpu.train import checkpoint as ckpt
from xflow_tpu.train.checkpoint import (
    committed_steps,
    mirror_step,
    read_data_state,
    tier_steps,
)
from xflow_tpu.train.trainer import Trainer

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FAULT_ENVS = (
    "XFLOW_FAULT_CKPT_ENOSPC_BYTES",
    "XFLOW_FAULT_CKPT_SLOW_S_PER_MB",
    "XFLOW_FAULT_CKPT_TIER",
)


@pytest.fixture(autouse=True)
def _clean_fault_env(monkeypatch):
    for name in FAULT_ENVS + ("XFLOW_FAULT_KILL_STEP",):
        monkeypatch.delenv(name, raising=False)


def make_cfg(root, **kw):
    base = {
        "data.train_path": str(root / "train"),
        "data.log2_slots": 12,
        "data.batch_size": 100,
        "data.max_nnz": 8,
        "model.num_fields": 5,
        "train.epochs": 2,
        "train.pred_dump": False,
    }
    base.update(kw)
    return override(Config(), **base)


@pytest.fixture
def dataset(tmp_path):
    generate_shards(
        str(tmp_path / "train"), 1, 600, num_fields=5, ids_per_field=30,
        seed=0,
    )
    return tmp_path


def read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# ------------------------------------------------------ fault injector unit
def test_ckpt_write_fault_env_contract(monkeypatch, tmp_path):
    """ENOSPC budget + tier targeting, resolved fresh per save."""
    assert ckpt_write_fault("primary") is None  # nothing armed
    p = tmp_path / "blob"
    p.write_bytes(b"x" * 1000)
    monkeypatch.setenv("XFLOW_FAULT_CKPT_ENOSPC_BYTES", "1500")
    f = ckpt_write_fault("primary")
    f(str(p))  # 1000 staged bytes: under budget
    with pytest.raises(OSError) as ei:
        f(str(p))  # cumulative 2000 > 1500
    assert "ENOSPC" in str(ei.value)
    # a FRESH resolve gets a fresh budget (per save, not per run)
    ckpt_write_fault("primary")(str(p))
    # tier targeting: a replica-only fault leaves the primary unarmed
    monkeypatch.setenv("XFLOW_FAULT_CKPT_TIER", "replica")
    assert ckpt_write_fault("primary") is None
    assert ckpt_write_fault("replica") is not None


# -------------------------------------------------- replica walk-back matrix
FM_BASE = {
    # the fullshard engine's validated shape (test_topology idiom); the
    # fused fm "wv" table also exercises the packed/logical layout
    # bridge every engine restore must cross
    "model.name": "fm",
    "data.log2_slots": 14,
    "data.batch_size": 128,
}


@pytest.fixture(scope="module")
def tiered_runs(tmp_path_factory):
    """One fit per format with both tiers committed; the matrix cases
    below damage COPIES, so two fits serve all sixteen cases."""
    runs = {}
    for fmt in ("npz", "orbax"):
        if fmt == "orbax":
            pytest.importorskip("orbax.checkpoint")
        root = tmp_path_factory.mktemp(f"tiered_{fmt}")
        generate_shards(
            str(root / "train"), 1, 600, num_fields=5, ids_per_field=30,
            seed=0,
        )
        cfg = make_cfg(root, **FM_BASE, **{
            "train.checkpoint_dir": str(root / "ck"),
            "train.ckpt_replica_dir": str(root / "replica"),
            "train.checkpoint_every": 5,
            "train.checkpoint_format": fmt,
        })
        t = Trainer(cfg)
        t.fit()
        steps = tier_steps(str(root / "ck"), fmt)
        assert len(steps) >= 2  # cadence + final: a walk-back target
        assert tier_steps(str(root / "replica"), fmt) == steps
        runs[fmt] = {
            "root": root,
            "steps": steps,
            "wv": np.asarray(jax.device_get(t.state.tables["wv"])).copy(),
            "examples": read_data_state(
                str(root / "replica"), steps[0], fmt=fmt)["examples"],
        }
    return runs


def copy_tiers(src_root, tmp_path):
    primary = str(tmp_path / "ck")
    replica = str(tmp_path / "replica")
    shutil.copytree(str(src_root / "ck"), primary)
    shutil.copytree(str(src_root / "replica"), replica)
    return primary, replica


ENGINES = ("single", "gspmd", "replicated", "fullshard")


def engine_trainer(cfg, engine):
    from xflow_tpu.parallel.mesh import make_mesh

    if engine == "single":
        return Trainer(cfg)
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 CPU devices")
    if engine == "gspmd":
        # sorted engines off -> the generic GSPMD mesh path
        cfg = override(cfg, **{"data.sorted_layout": "off"})
    elif engine == "replicated":
        cfg = override(cfg, **{"data.sorted_layout": "on",
                               "data.sorted_mesh": "replicated"})
    mesh = make_mesh(cfg, np.array(jax.devices()[:2]))
    t = Trainer(cfg, mesh=mesh)
    if engine == "fullshard":
        assert t._mesh_engine == "fullshard"
    elif engine == "replicated":
        assert t._mesh_engine == "replicated"
    else:
        assert t._mesh_engine is None
    return t


@pytest.mark.parametrize("engine", ENGINES)
@pytest.mark.parametrize("fmt", ("npz", "orbax"))
@pytest.mark.parametrize("damage", ("missing", "bitflip"))
def test_replica_walkback_matrix(tiered_runs, tmp_path, engine, fmt, damage):
    """THE tier-2 acceptance matrix: with the newest primary step gone
    or digest-poisoned, every engine restores the SAME step from the
    replica mirror — same logical table bytes, same step, and the
    data-stream position travels from the tier that restored."""
    src = tiered_runs[fmt]
    newest = src["steps"][0]
    primary, replica = copy_tiers(src["root"], tmp_path)
    if damage == "missing":
        prefix = "orbax_step_" if fmt == "orbax" else "step_"
        shutil.rmtree(os.path.join(primary, f"{prefix}{newest}"))
    elif fmt == "orbax":
        corrupt_orbax_checkpoint(primary, step=newest, mode="bitflip",
                                 target="largest")
    else:
        corrupt_npz_checkpoint(primary, step=newest, mode="bitflip")

    cfg = make_cfg(src["root"], **FM_BASE, **{
        "train.checkpoint_dir": primary,
        "train.ckpt_replica_dir": replica,
        "train.checkpoint_format": fmt,
        "train.resume": True,
    })
    t = engine_trainer(cfg, engine)
    assert t.maybe_restore()
    assert int(t.state.step) == newest
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(t.state.tables["wv"])), src["wv"],
        err_msg=f"{engine}/{fmt}/{damage}: replica restore drifted",
    )
    assert t._resume_data_state is not None
    assert t._resume_data_state["examples"] == src["examples"]


def test_replica_divergence_walks_to_older_step(tiered_runs, tmp_path):
    """Both copies of the newest step bad (primary missing, replica
    poisoned — the replica-divergence row of the failure matrix): the
    walk continues to the previous committed step instead of restoring
    garbage or dying."""
    src = tiered_runs["npz"]
    newest, older = src["steps"][0], src["steps"][1]
    primary, replica = copy_tiers(src["root"], tmp_path)
    shutil.rmtree(os.path.join(primary, f"step_{newest}"))
    corrupt_npz_checkpoint(replica, step=newest, mode="bitflip")
    cfg = make_cfg(src["root"], **FM_BASE, **{
        "train.checkpoint_dir": primary,
        "train.ckpt_replica_dir": replica,
        "train.resume": True,
    })
    t = Trainer(cfg)
    assert t.maybe_restore()
    assert int(t.state.step) == older
    assert t._resume_data_state == read_data_state(primary, older)


def test_mirror_step_idempotent_and_committed_last(tiered_runs, tmp_path):
    """mirror_step re-run on an already-committed replica step is a
    no-op, and a fresh mirror lands digest-verified with its own
    COMMITTED marker."""
    src = tiered_runs["npz"]
    newest = src["steps"][0]
    primary = str(src["root"] / "ck")
    replica = str(tmp_path / "replica2")
    dst = mirror_step(primary, replica, newest)
    assert os.path.exists(os.path.join(dst, "COMMITTED"))
    assert committed_steps(replica) == [newest]
    before = sorted(os.listdir(dst))
    mtime = os.path.getmtime(os.path.join(dst, "state.npz"))
    assert mirror_step(primary, replica, newest) == dst  # idempotent
    assert sorted(os.listdir(dst)) == before
    assert os.path.getmtime(os.path.join(dst, "state.npz")) == mtime


# ------------------------------------------------------- skip-on-busy + off
def test_async_skip_on_busy_accounting(dataset, tmp_path, monkeypatch,
                                       capsys):
    """Cadence hit while a save is in flight = one logged, counted skip
    — never a queue. The slow-write fault pins the step-5 save in
    flight across the step-10 cadence; the end-of-fit wait=True save
    still commits step 12."""
    # ~48KB state * 60 s/MB ≈ 3s per staged file — far longer than the
    # fit needs to reach the step-10 cadence
    monkeypatch.setenv("XFLOW_FAULT_CKPT_SLOW_S_PER_MB", "60")
    ck = str(tmp_path / "ck")
    cfg = make_cfg(dataset, **{
        "train.checkpoint_dir": ck,
        "train.checkpoint_every": 5,
        "train.ckpt_async": True,
        "train.metrics_path": str(tmp_path / "metrics.jsonl"),
    })
    t = Trainer(cfg)
    res = t.fit()
    assert res.steps == 12
    assert t._ckpt_writer is None  # fit() closed the writer
    assert committed_steps(ck) == [12, 5]  # 10 skipped, final waited
    recs = [r for r in read_jsonl(str(tmp_path / "metrics.jsonl"))
            if r.get("kind") == "ckpt"]
    events = {(r["step"], r["event"]) for r in recs}
    assert (5, "committed") in events
    assert (10, "skipped") in events
    assert (12, "committed") in events
    assert max(r["skips"] for r in recs) == 1
    skipped = next(r for r in recs if r["event"] == "skipped")
    assert skipped["write_ms"] == 0.0 and skipped["tier"] == "primary"
    assert not any(r["degraded"] for r in recs)
    assert "previous save still in flight" in capsys.readouterr().err


def test_async_off_identical_artifact_no_records(dataset, tmp_path):
    """The ckpt_async=off pin: no writer thread and no kind="ckpt"
    records; and the async pipeline reorders work without changing the
    artifact — same step, same per-array digests, same data_state."""
    ck_sync = str(tmp_path / "ck_sync")
    cfg = make_cfg(dataset, **{
        "train.checkpoint_dir": ck_sync,
        "train.metrics_path": str(tmp_path / "m_sync.jsonl"),
    })
    t = Trainer(cfg)
    t.fit()
    assert t._ckpt_writer is None  # never started
    assert all(r.get("kind") != "ckpt"
               for r in read_jsonl(str(tmp_path / "m_sync.jsonl")))

    ck_async = str(tmp_path / "ck_async")
    Trainer(make_cfg(dataset, **{
        "train.checkpoint_dir": ck_async,
        "train.ckpt_async": True,
    })).fit()
    assert committed_steps(ck_sync) == committed_steps(ck_async) == [12]
    meta_s = ckpt.read_meta(ck_sync, 12)
    meta_a = ckpt.read_meta(ck_async, 12)
    assert meta_s["digests"] == meta_a["digests"]
    assert meta_s["layout"] == meta_a["layout"]
    assert read_data_state(ck_sync, 12) == read_data_state(ck_async, 12)


# --------------------------------------------------------- degraded mode
def test_enospc_degrades_to_replica_only(dataset, tmp_path, monkeypatch,
                                         capsys):
    """A primary-tier ENOSPC mid-save latches degraded mode: training
    finishes, every save lands as a FULL save on the replica tier, the
    kind="ckpt" trail says so, and the resume restores from the
    replica."""
    monkeypatch.setenv("XFLOW_FAULT_CKPT_ENOSPC_BYTES", "1")
    monkeypatch.setenv("XFLOW_FAULT_CKPT_TIER", "primary")
    ck = str(tmp_path / "ck")
    replica = str(tmp_path / "replica")
    cfg = make_cfg(dataset, **{
        "train.checkpoint_dir": ck,
        "train.ckpt_replica_dir": replica,
        "train.checkpoint_every": 5,
        "train.ckpt_async": True,
        "train.metrics_path": str(tmp_path / "metrics.jsonl"),
    })
    res = Trainer(cfg).fit()
    assert res.steps == 12  # training never stopped
    assert committed_steps(ck) == []  # the primary volume is "full"
    assert committed_steps(replica)[0] == 12
    recs = [r for r in read_jsonl(str(tmp_path / "metrics.jsonl"))
            if r.get("kind") == "ckpt"]
    assert any(r["tier"] == "primary" and r["event"] == "failed"
               for r in recs)
    assert any(r["tier"] == "replica" and r["event"] == "committed"
               and r["degraded"] for r in recs)
    assert "degrading to replica-only" in capsys.readouterr().err
    # the resume walks the union: replica-only steps restore fine
    for name in FAULT_ENVS:
        monkeypatch.delenv(name, raising=False)
    t2 = Trainer(override(cfg, **{"train.resume": True}))
    assert t2.maybe_restore() and int(t2.state.step) == 12


def test_sync_mirror_failure_never_harms_primary(dataset, tmp_path,
                                                 monkeypatch, capsys):
    """Synchronous mode with a replica-targeted fault: the primary
    commit stands, the mirror failure is a logged warning, training and
    the final save finish."""
    monkeypatch.setenv("XFLOW_FAULT_CKPT_ENOSPC_BYTES", "1")
    monkeypatch.setenv("XFLOW_FAULT_CKPT_TIER", "replica")
    ck = str(tmp_path / "ck")
    replica = str(tmp_path / "replica")
    cfg = make_cfg(dataset, **{
        "train.checkpoint_dir": ck,
        "train.ckpt_replica_dir": replica,
    })
    res = Trainer(cfg).fit()
    assert res.steps == 12
    assert committed_steps(ck) == [12]
    assert committed_steps(replica) == []
    assert "the primary commit stands" in capsys.readouterr().err


# ------------------------------------------------- kill mid-async-save
@pytest.mark.slow
def test_kill_mid_async_save_resume_parity(dataset, tmp_path):
    """The acceptance drill: SIGKILL lands while the background writer
    is mid-write (slow-write paced), the torn step is uncommitted
    debris, and the relaunch walks back, replays the exact lost
    examples, and converges to the uninterrupted run's state."""
    ref = Trainer(make_cfg(dataset))
    assert ref.fit().steps == 12

    ck = str(tmp_path / "ck")
    base_env = dict(os.environ)
    base_env["PYTHONPATH"] = REPO_ROOT + os.pathsep + base_env.get(
        "PYTHONPATH", "")
    base_env["JAX_PLATFORMS"] = "cpu"

    def train_args(*extra_sets):
        args = [
            sys.executable, "-m", "xflow_tpu", "train",
            "--train", str(dataset / "train"), "--epochs", "2",
            "--batch-size", "100", "--log2-slots", "12", "--no-mesh",
            "--checkpoint-dir", ck,
            "--set", "model.num_fields=5", "--set", "data.max_nnz=8",
            "--set", "train.pred_dump=false",
            "--set", "train.checkpoint_every=5",
            "--set", "train.resume=true",
        ]
        for s in extra_sets:
            args += ["--set", s]
        return args

    # phase A: synchronous saves (deterministic commit), die after the
    # step-7 boundary — committed exactly [5]
    env = dict(base_env)
    env["XFLOW_FAULT_KILL_STEP"] = "7"
    r = subprocess.run(train_args(), capture_output=True, text=True,
                       timeout=300, env=env)
    assert r.returncode != 0  # SIGKILLed
    assert committed_steps(ck) == [5], r.stderr

    # phase B: resume from 5 with async on and the step-10 save paced
    # to ~30s; the kill at global step 11 (the injector counts THIS
    # process's steps: local 6) lands MID-WRITE — torn, uncommitted
    env = dict(base_env)
    env["XFLOW_FAULT_KILL_STEP"] = "6"
    env["XFLOW_FAULT_CKPT_SLOW_S_PER_MB"] = "600"
    env["XFLOW_FAULT_CKPT_TIER"] = "primary"
    r = subprocess.run(train_args("train.ckpt_async=true"),
                       capture_output=True, text=True, timeout=300,
                       env=env)
    assert r.returncode != 0
    assert "resumed from step 5" in r.stderr
    assert committed_steps(ck) == [5], r.stderr
    assert os.path.isdir(os.path.join(ck, "step_10"))  # the torn save
    assert not os.path.exists(os.path.join(ck, "step_10", "COMMITTED"))

    # phase C: faults disarmed — the walk-back resume sweeps the
    # debris, retrains 6..12, and matches the uninterrupted run exactly
    r = subprocess.run(train_args("train.ckpt_async=true"),
                       capture_output=True, text=True, timeout=300,
                       env=base_env)
    assert r.returncode == 0, r.stderr
    assert "resumed from step 5" in r.stderr
    assert committed_steps(ck)[0] == 12
    t = Trainer(make_cfg(dataset, **{"train.checkpoint_dir": ck,
                                     "train.resume": True}))
    assert t.maybe_restore() and int(t.state.step) == 12
    np.testing.assert_allclose(
        np.asarray(t.state.tables["w"]), np.asarray(ref.state.tables["w"]),
        rtol=0, atol=1e-6,
        err_msg="kill-mid-async-save resume drifted from the "
                "uninterrupted stream",
    )
    ds = read_data_state(ck, 12)
    assert ds["completed"] and ds["examples"] == 1200


# --------------------------------------------------------- CLI + telemetry
def test_corrupt_ckpt_cli_tier_replica(tiered_runs, tmp_path):
    """The operator drill reaches the replica tier end to end: the CLI
    poisons the mirror, and the mirror then fails its digest check."""
    src = tiered_runs["npz"]
    newest = src["steps"][0]
    replica = str(tmp_path / "replica")
    shutil.copytree(str(src["root"] / "replica"), replica)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools",
                                      "corrupt_ckpt.py"),
         "--dir", "ignored", "--tier", "replica", "--replica-dir", replica,
         "--mode", "bitflip"],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stderr
    out = json.loads(r.stdout)
    assert out["tier"] == "replica"
    assert out["corrupted"].startswith(replica)
    like = Trainer(make_cfg(src["root"], **FM_BASE)).state
    with pytest.raises(ckpt.CheckpointDigestError):
        ckpt.restore(replica, like, step=newest)


def _ck_rec(step, tier, event, q, c, skips=0, **kw):
    rec = {"ts": c, "rank": 0, "run_id": "r", "kind": "ckpt", "step": step,
           "tier": tier, "event": event, "queued_ts": q, "committed_ts": c,
           "queue_ms": 1.0, "write_ms": 2.0, "bytes": 100, "skips": skips,
           "degraded": False}
    rec.update(kw)
    return rec


def _check(dirpath, recs):
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    import metrics_report

    os.makedirs(dirpath, exist_ok=True)
    path = os.path.join(dirpath, "metrics_rank0.jsonl")
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    streams, _ = metrics_report.load_streams([path])
    return metrics_report.check_streams(streams, [path])


def test_metrics_report_ckpt_gate(tmp_path):
    """--check on kind="ckpt": all-or-none keys, tier/event vocabulary,
    commit-after-queue causality, non-overlapping intervals per tier,
    skip counter monotone — a good stream is clean, each violation is
    named."""
    good = [
        _ck_rec(5, "primary", "committed", 1.0, 2.0),
        _ck_rec(5, "replica", "committed", 1.0, 2.5),
        _ck_rec(10, "primary", "skipped", 3.0, 3.0, skips=1,
                write_ms=0.0),
        _ck_rec(12, "primary", "committed", 4.0, 5.0, skips=1),
        _ck_rec(12, "replica", "committed", 4.0, 5.5, skips=1),
    ]
    assert _check(tmp_path / "good", good) == []

    bad = [dict(good[0])]
    del bad[0]["queue_ms"]
    assert any("lacks ckpt keys" in p for p in _check(tmp_path / "m", bad))

    assert any("unknown ckpt tier" in p for p in _check(
        tmp_path / "t", [_ck_rec(5, "tertiary", "committed", 1.0, 2.0)]))

    assert any("unknown ckpt event" in p for p in _check(
        tmp_path / "e", [_ck_rec(5, "primary", "exploded", 1.0, 2.0)]))

    assert any("cannot commit" in p for p in _check(
        tmp_path / "c", [_ck_rec(5, "primary", "committed", 3.0, 2.0)]))

    # two saves in flight: the second commit's queued_ts predates the
    # first one's committed_ts on the same tier...
    assert any("two saves in flight" in p for p in _check(
        tmp_path / "o",
        [_ck_rec(5, "primary", "committed", 1.0, 4.0),
         _ck_rec(10, "primary", "committed", 3.0, 5.0)]))
    # ...but a replica interval sharing its job's queued_ts is FINE
    assert _check(tmp_path / "s",
                  [_ck_rec(5, "primary", "committed", 1.0, 2.0),
                   _ck_rec(5, "replica", "committed", 1.0, 2.5)]) == []

    assert any("skip counter went backwards" in p for p in _check(
        tmp_path / "k",
        [_ck_rec(5, "primary", "committed", 1.0, 2.0, skips=2),
         _ck_rec(12, "primary", "committed", 3.0, 4.0, skips=1)]))


def test_metrics_report_health_ckpt_section(dataset, tmp_path):
    """--health names the last committed step per tier; --check passes
    a real async run's stream."""
    mpath = str(tmp_path / "metrics.jsonl")
    cfg = make_cfg(dataset, **{
        "train.checkpoint_dir": str(tmp_path / "ck"),
        "train.ckpt_replica_dir": str(tmp_path / "replica"),
        "train.checkpoint_every": 5,
        "train.ckpt_async": True,
        "train.metrics_path": mpath,
    })
    Trainer(cfg).fit()
    tool = os.path.join(REPO_ROOT, "tools", "metrics_report.py")
    r = subprocess.run([sys.executable, tool, mpath, "--health"],
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr
    assert "checkpoints (kind=ckpt" in r.stdout
    assert "primary: last committed step 12" in r.stdout
    assert "replica: last committed step 12" in r.stdout
    r2 = subprocess.run([sys.executable, tool, mpath, "--check"],
                        capture_output=True, text=True, timeout=300)
    assert r2.returncode == 0, r2.stdout + r2.stderr


# ------------------------------------------------------------- serve tier
def test_serve_watcher_follows_replica_tier(tiered_runs, tmp_path):
    """The hot-reload watcher's view spans both tiers: with the primary
    copy of the newest step digest-poisoned, latest_committed_step
    still reports it and load() swaps it in from the replica."""
    from xflow_tpu.serve.runner import ServeRunner

    src = tiered_runs["npz"]
    newest = src["steps"][0]
    primary, replica = copy_tiers(src["root"], tmp_path)
    corrupt_npz_checkpoint(primary, step=newest, mode="bitflip")
    cfg = make_cfg(src["root"], **FM_BASE, **{
        "train.checkpoint_dir": primary,
        "train.ckpt_replica_dir": replica,
    })
    runner = ServeRunner(cfg)
    assert runner.latest_committed_step() == newest
    gen = runner.load()
    assert gen.step == newest
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(gen.tables["wv"])), src["wv"],
        err_msg="serve-side replica restore drifted",
    )


# ---------------------------------------------------------------- CI gate
@pytest.mark.slow
def test_smoke_durable_script(tmp_path):
    """The durability CI gate end to end: async stall collapse through
    perf_ledger --regress, SIGKILL mid-async-save + exact accounting,
    poisoned primary + serve-side replica hot reload with zero dropped
    requests, metrics_report --check green (tools/smoke_durable.sh)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        ["bash", os.path.join(REPO_ROOT, "tools", "smoke_durable.sh"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=570, env=env,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "smoke_durable: OK" in r.stdout
    bench = json.load(open(tmp_path / "BENCH_CKPT.json"))
    by_round = {b["round"]: b["value"] for b in bench}
    assert set(by_round) == {1, 2}
    assert by_round[2] < by_round[1]  # async stall < sync stall
