"""Multi-threaded parser pool: byte-parity with the sequential parser,
deterministic ordering, block-boundary edge cases, and throughput.

Reference analog: the worker thread pool that fans parsing over
hardware_concurrency() threads (`/root/reference/src/base/thread_pool.h:70-86`,
`lr_worker.cc:190-199`) — but deterministic: blocks are reassembled in
file order, so the MT stream is byte-identical to the sequential one.
"""

import dataclasses
import shutil
import time

import numpy as np
import pytest

from xflow_tpu.config import DataConfig
from xflow_tpu.data.synth import generate_shards

pytestmark = pytest.mark.skipif(shutil.which("g++") is None, reason="no g++")


def _batches(path, cfg, bs):
    from xflow_tpu.data import native

    return list(native.native_batch_iterator(path, cfg, bs))


def _assert_same(a_list, b_list):
    assert len(a_list) == len(b_list)
    for a, b in zip(a_list, b_list):
        np.testing.assert_array_equal(a.slots, b.slots)
        np.testing.assert_array_equal(a.fields, b.fields)
        np.testing.assert_array_equal(a.mask, b.mask)
        np.testing.assert_array_equal(a.labels, b.labels)
        np.testing.assert_array_equal(a.row_mask, b.row_mask)


@pytest.mark.parametrize("block", [4096, 1 << 16, 2 << 20])
def test_mt_parity_with_sequential(tmp_path, block):
    path = generate_shards(str(tmp_path / "s"), 1, 4000, num_fields=9,
                           ids_per_field=300, seed=11)[0]
    seq = dataclasses.replace(DataConfig(log2_slots=18, max_nnz=12), parser_threads=1,
                              block_bytes=block)
    mt = dataclasses.replace(seq, parser_threads=4)
    _assert_same(_batches(path, seq, 256), _batches(path, mt, 256))


def test_mt_parity_on_edge_file(tmp_path):
    # block boundaries landing on newlines, CRLF, junk, unterminated tail
    p = tmp_path / "edge-00000"
    lines = []
    for i in range(500):
        lines.append(f"{i % 2}\t0:{i}:1 1:{i * 7}:1")
    body = "\n".join(lines) + "\r\n1\tfoo\n\n0.5\t1:3:1"  # no trailing newline
    p.write_text(body)
    seq = dataclasses.replace(DataConfig(log2_slots=14, max_nnz=4),
                              parser_threads=1, block_bytes=4096)
    # tiny blocks (min 4096) force many boundary crossings
    mt = dataclasses.replace(seq, parser_threads=8)
    _assert_same(_batches(str(p), seq, 64), _batches(str(p), mt, 64))


def test_mt_single_line_spanning_blocks(tmp_path):
    # one line far longer than block_bytes: only the block containing its
    # first byte parses it
    p = tmp_path / "long-00000"
    toks = " ".join(f"0:{i}:1" for i in range(3000))  # ~26KB line
    p.write_text(f"1\t{toks}\n0\t1:5:1\n")
    seq = dataclasses.replace(DataConfig(log2_slots=14, max_nnz=4000),
                              parser_threads=1, block_bytes=4096)
    mt = dataclasses.replace(seq, parser_threads=4)
    a, b = _batches(str(p), seq, 8), _batches(str(p), mt, 8)
    _assert_same(a, b)
    assert a[0].num_rows == 2
    assert a[0].mask[0].sum() == 3000


def test_mt_truncation_counter(tmp_path):
    p = tmp_path / "t-00000"
    p.write_text("1\t0:1:1 1:2:1 2:3:1 3:4:1\n" * 100)
    from xflow_tpu.data import native

    cfg = dataclasses.replace(DataConfig(log2_slots=10, max_nnz=2), parser_threads=4)
    stream = native._NativeBatchStream(str(p), cfg, 32)
    list(stream)
    assert stream.truncated == 200  # 2 over-cap features x 100 rows


def test_mt_throughput_target(tmp_path):
    # VERDICT round-1 item 4: parser >= 4M rows/s aggregate. The scaling
    # assertion needs cores to scale over — this CI image exposes ONE CPU
    # core (os.cpu_count() == 1), where no thread pool (including the
    # reference's hardware_concurrency() pool) can beat sequential, so
    # there the test asserts parity + bounded overhead only.
    import os

    rows = 300_000
    path = generate_shards(str(tmp_path / "big"), 1, rows, num_fields=18,
                           ids_per_field=100_000, seed=12)[0]
    seq = dataclasses.replace(DataConfig(log2_slots=22, max_nnz=20), parser_threads=1)
    mt = dataclasses.replace(seq, parser_threads=0)  # auto
    # warm the page cache
    with open(path, "rb") as f:
        f.read()
    t0 = time.perf_counter()
    n_seq = sum(b.num_rows for b in _batches(path, seq, 4096))
    t_seq = time.perf_counter() - t0
    t0 = time.perf_counter()
    n_mt = sum(b.num_rows for b in _batches(path, mt, 4096))
    t_mt = time.perf_counter() - t0
    assert n_seq == n_mt == rows
    cores = os.cpu_count() or 1
    if cores >= 4:
        mt_rate = rows / t_mt
        assert mt_rate > 4_000_000, f"MT parser {mt_rate:.0f} rows/s < 4M target"
        assert t_mt < t_seq / 2, (t_seq, t_mt)
    else:
        # single-core: auto mode must fall back to the sequential parser
        # (no MT overhead) and stay within noise of it
        assert t_mt < t_seq * 1.3, (t_seq, t_mt)
