"""Sorted-window table engine (ops/sorted_table.py): plan correctness,
gather/scatter parity vs direct XLA ops, custom-VJP gradients, and FM
forward/step equality between the sorted and row-major paths."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from xflow_tpu.config import Config, override
from xflow_tpu.models import get_model
from xflow_tpu.ops.sorted_table import (
    CHUNK,
    WINDOW,
    _gather_pallas,
    _gather_xla,
    _k8,
    _scatter_pallas,
    _scatter_xla,
    plan_sorted_batch,
    table_gather_sorted,
)

S = 2 * WINDOW
K = 11
K8 = _k8(K)


def _random_case(rng, B=16, F=8, mask_p=0.7):
    slots = rng.integers(0, S, (B, F)).astype(np.int32)
    mask = (rng.random((B, F)) < mask_p).astype(np.float32)
    table = rng.normal(size=(S, K)).astype(np.float32)
    return slots, mask, table


def test_plan_invariants():
    rng = np.random.default_rng(0)
    slots, mask, _ = _random_case(rng)
    plan = plan_sorted_batch(slots, mask, S)
    n = slots.size
    assert plan.sorted_slots.shape[0] % CHUNK == 0
    assert plan.sorted_slots.shape[0] >= n + CHUNK
    assert np.all(np.diff(plan.sorted_slots) >= 0)  # sorted incl. pads
    assert np.all(plan.sorted_slots[n:] == S - 1)  # pad = last slot, mask 0
    assert np.all(plan.sorted_mask[n:] == 0.0)
    assert plan.win_off.shape == (S // WINDOW + 1,)
    # every position (pads included) is owned by some window
    assert plan.win_off[0] == 0 and plan.win_off[-1] == plan.sorted_slots.shape[0]
    # every occurrence is within its window's range
    for t in range(S // WINDOW):
        seg = plan.sorted_slots[plan.win_off[t] : plan.win_off[t + 1]]
        assert np.all((seg >= t * WINDOW) & (seg < (t + 1) * WINDOW))
    # permutation round-trip: multiset of (slot, mask) pairs preserved
    got = sorted(zip(plan.sorted_slots[:n].tolist(), plan.sorted_mask[:n].tolist()))
    want = sorted(zip(slots.ravel().tolist(), mask.ravel().tolist()))
    assert got == want


def test_gather_sorted_matches_direct():
    rng = np.random.default_rng(1)
    slots, mask, table = _random_case(rng)
    plan = plan_sorted_batch(slots, mask, S)
    occ_t = table_gather_sorted(
        jnp.asarray(table), jnp.asarray(plan.sorted_slots), jnp.asarray(plan.win_off)
    )
    n = slots.size
    assert occ_t.shape == (K8, plan.sorted_slots.shape[0])
    np.testing.assert_allclose(
        np.asarray(occ_t[:K, :n]).T, table[plan.sorted_slots[:n]], rtol=1e-6
    )
    # pad cols hold row S-1's values (owned by the last window, never
    # uninitialized memory); consumers mask them out via sorted_mask
    np.testing.assert_allclose(
        np.asarray(occ_t[:K, n:]).T,
        np.broadcast_to(table[S - 1], (occ_t.shape[1] - n, K)),
        rtol=1e-6,
    )
    np.testing.assert_array_equal(np.asarray(occ_t[K:]), 0.0)  # pad rows


def test_scatter_vjp_matches_xla_scatter():
    rng = np.random.default_rng(2)
    slots, mask, table = _random_case(rng, B=32, F=16)
    plan = plan_sorted_batch(slots, mask, S)
    n = slots.size
    np_len = plan.sorted_slots.shape[0]
    d_t = rng.normal(size=(K8, np_len)).astype(np.float32)
    d_t[K:] = 0.0
    d_t[:, n:] = 0.0

    def f(tab):
        occ_t = table_gather_sorted(
            tab, jnp.asarray(plan.sorted_slots), jnp.asarray(plan.win_off)
        )
        return (occ_t * jnp.asarray(d_t)).sum()

    d_table = jax.grad(f)(jnp.asarray(table))
    want = np.zeros((S, K), np.float32)
    np.add.at(want, plan.sorted_slots[:n], d_t[:K, :n].T)
    np.testing.assert_allclose(np.asarray(d_table), want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("seed", [3, 4])
def test_pallas_interpret_matches_xla(seed):
    # the TPU kernels, run in interpreter mode, must equal the XLA path
    pltpu = pytest.importorskip("jax.experimental.pallas.tpu")

    if not hasattr(pltpu, "force_tpu_interpret_mode"):
        pytest.skip("pallas TPU interpret mode unavailable in this jax build")
    rng = np.random.default_rng(seed)
    slots, mask, table = _random_case(rng, B=24, F=11)
    plan = plan_sorted_batch(slots, mask, S)
    n = slots.size
    np_len = plan.sorted_slots.shape[0]
    jt = jnp.asarray(table)
    jss = jnp.asarray(plan.sorted_slots)
    joff = jnp.asarray(plan.win_off)
    with pltpu.force_tpu_interpret_mode():
        occ_p = _gather_pallas(jt, jss, joff)
    occ_x = _gather_xla(jt, jss, joff)
    # rtol 5e-5, not exact: the kernels' 3-term bf16 decomposition
    # (_dot_f32) reconstructs f32 bit-exactly on the real MXU
    # (verified on-device against the XLA gather), but the INTERPRETER's
    # bf16 rounding emulation can drop the low term's last ulp on rare
    # elements (~2^-16 relative). This test gates the structural parity
    # (windows, blend, offsets), not MXU arithmetic.
    np.testing.assert_allclose(
        np.asarray(occ_p[:K, :n]), np.asarray(occ_x[:K, :n]), rtol=5e-5
    )

    d_t = jnp.asarray(rng.normal(size=(K8, np_len)).astype(np.float32))
    with pltpu.force_tpu_interpret_mode():
        dt_p = _scatter_pallas(d_t, jss, joff, S, K)
    dt_x = _scatter_xla(d_t, jss, joff, S, K)
    # same interpreter-emulation tolerance as the gather above
    np.testing.assert_allclose(np.asarray(dt_p), np.asarray(dt_x), rtol=5e-5, atol=2e-5)


def test_rowsum_pallas_interpret_matches_xla():
    # the TPU row-sum kernel (scalar-core RMW into a VMEM-resident
    # accumulator), run in interpreter mode, must equal segment_sum
    pltpu = pytest.importorskip("jax.experimental.pallas.tpu")

    from xflow_tpu.ops.sorted_table import _rowsum_pallas

    if not hasattr(pltpu, "force_tpu_interpret_mode"):
        pytest.skip("pallas TPU interpret mode unavailable in this jax build")
    rng = np.random.default_rng(17)
    n, ch, rows_n = CHUNK, 24, 40
    rows = jnp.asarray(rng.integers(0, rows_n, n).astype(np.int32))
    vals = jnp.asarray(rng.normal(size=(ch, n)).astype(np.float32))
    with pltpu.force_tpu_interpret_mode():
        got = _rowsum_pallas(vals, rows, rows_n)
    want = jax.ops.segment_sum(vals.T, rows, num_segments=rows_n)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_rowsum_grad_matches_segment_sum():
    from xflow_tpu.ops.sorted_table import row_sums_sorted

    rng = np.random.default_rng(18)
    n, ch, rows_n = CHUNK, 8, 12
    rows = jnp.asarray(rng.integers(0, rows_n, n).astype(np.int32))
    vals = jnp.asarray(rng.normal(size=(ch, n)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(rows_n, ch)).astype(np.float32))

    def f_custom(v):
        return (row_sums_sorted(v, rows, rows_n) * w).sum()

    def f_ref(v):
        return (jax.ops.segment_sum(v.T, rows, num_segments=rows_n) * w).sum()

    np.testing.assert_allclose(
        np.asarray(jax.grad(f_custom)(vals)), np.asarray(jax.grad(f_ref)(vals)),
        rtol=1e-5, atol=1e-6,
    )


def test_native_plan_matches_numpy(monkeypatch):
    """xf_plan_sorted (C radix sort) is bit-identical to the numpy
    argsort planner — both stable, same pads, same win_off."""
    pytest.importorskip("ctypes")
    try:
        from xflow_tpu.data.native import native_plan_sorted  # noqa: F401 — builds lib
        from xflow_tpu.data.native import get_lib

        get_lib()
    except Exception:
        pytest.skip("native toolchain unavailable")
    import xflow_tpu.ops.sorted_table as st

    rng = np.random.default_rng(21)
    for B, F, with_fields in [(16, 8, False), (64, 8, True), (1, 1, False), (7, 3, True)]:
        slots = rng.integers(0, S, (B, F)).astype(np.int32)
        mask = (rng.random((B, F)) < 0.7).astype(np.float32)
        fields = rng.integers(0, 6, (B, F)).astype(np.int32) if with_fields else None

        monkeypatch.setattr(st, "_NATIVE_PLAN", None)
        monkeypatch.setenv("XFLOW_NO_NATIVE_PLAN", "1")
        py = st.plan_sorted_batch(slots, mask, S, fields=fields)
        monkeypatch.delenv("XFLOW_NO_NATIVE_PLAN")
        monkeypatch.setattr(st, "_NATIVE_PLAN", None)
        nat = st.plan_sorted_batch(slots, mask, S, fields=fields)
        assert st._NATIVE_PLAN, "native planner did not engage"

        np.testing.assert_array_equal(nat.sorted_slots, py.sorted_slots)
        np.testing.assert_array_equal(nat.sorted_row, py.sorted_row)
        np.testing.assert_array_equal(nat.sorted_mask, py.sorted_mask)
        np.testing.assert_array_equal(nat.win_off, py.win_off)
        if with_fields:
            np.testing.assert_array_equal(nat.sorted_fields, py.sorted_fields)
        else:
            assert nat.sorted_fields is None and py.sorted_fields is None
    monkeypatch.setattr(st, "_NATIVE_PLAN", None)


@pytest.mark.parametrize("model_name, table", [("fm", "wv"), ("mvm", "v")])
def test_trainer_sorted_layout_matches_off(tmp_path, model_name, table):
    # end-to-end: identical final tables and AUC with the layout on vs off
    from xflow_tpu.data.synth import generate_shards
    from xflow_tpu.train.trainer import Trainer

    generate_shards(str(tmp_path / "train"), 1, 400, num_fields=5, ids_per_field=60, seed=7)

    def run(sorted_layout):
        cfg = override(
            Config(),
            **{
                "data.train_path": str(tmp_path / "train"),
                "data.test_path": str(tmp_path / "train"),
                "data.log2_slots": 12,
                "data.batch_size": 50,
                "data.max_nnz": 8,
                "data.sorted_layout": sorted_layout,
                "model.name": model_name,
                "model.num_fields": 5,
                "train.epochs": 2,
                "train.pred_dump": False,
            },
        )
        t = Trainer(cfg)
        assert t._sorted == (sorted_layout == "on")
        t.fit()
        return t

    t_on, t_off = run("on"), run("off")
    np.testing.assert_allclose(
        np.asarray(t_on.state.tables[table]), np.asarray(t_off.state.tables[table]),
        rtol=1e-4, atol=1e-6,
    )
    auc_on, _ = t_on.evaluate()
    auc_off, _ = t_off.evaluate()
    assert auc_on == pytest.approx(auc_off, abs=1e-6)


def test_mvm_sorted_forward_and_step_match_rowmajor():
    from xflow_tpu.optim import get_optimizer
    from xflow_tpu.train.state import TrainState
    from xflow_tpu.train.step import make_train_step

    cfg = override(Config(), **{"data.log2_slots": 12, "model.name": "mvm",
                                "model.v_dim": 3, "model.num_fields": 4,
                                "data.max_nnz": 6})
    assert cfg.num_slots == S
    model = get_model("mvm")
    rng = np.random.default_rng(9)
    B, F = 32, 6
    slots = rng.integers(0, S, (B, F)).astype(np.int32)
    fields = rng.integers(0, 4, (B, F)).astype(np.int32)
    mask = (rng.random((B, F)) < 0.8).astype(np.float32)
    v = (rng.normal(size=(S, 3)) * 0.1).astype(np.float32)
    labels = (rng.random(B) < 0.5).astype(np.float32)
    base = {
        "slots": jnp.asarray(slots),
        "fields": jnp.asarray(fields),
        "mask": jnp.asarray(mask),
        "labels": jnp.asarray(labels),
        "row_mask": jnp.ones((B,), jnp.float32),
    }
    plan = plan_sorted_batch(slots, mask, S, fields=fields)
    assert plan.sorted_fields is not None
    n = slots.size
    # fields ride the same permutation: multiset of (slot, field, mask)
    got = sorted(zip(plan.sorted_slots[:n].tolist(), plan.sorted_fields[:n].tolist(),
                     plan.sorted_mask[:n].tolist()))
    want = sorted(zip(slots.ravel().tolist(), fields.ravel().tolist(),
                      mask.ravel().tolist()))
    assert got == want
    srt = {
        **base,
        "sorted_slots": jnp.asarray(plan.sorted_slots),
        "sorted_row": jnp.asarray(plan.sorted_row),
        "sorted_mask": jnp.asarray(plan.sorted_mask),
        "sorted_fields": jnp.asarray(plan.sorted_fields),
        "win_off": jnp.asarray(plan.win_off),
    }
    out_r = model.forward({"v": jnp.asarray(v)}, base, cfg)
    out_s = model.forward({"v": jnp.asarray(v)}, srt, cfg)
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_r), rtol=1e-4, atol=1e-6)

    opt = get_optimizer("ftrl")
    step = make_train_step(model, opt, cfg)
    t0 = {"v": jnp.asarray(v)}
    s_r, m_r = step(TrainState(t0, opt.init_state(t0), jnp.zeros((), jnp.int32)), base)
    t1 = {"v": jnp.asarray(v)}
    s_s, m_s = step(TrainState(t1, opt.init_state(t1), jnp.zeros((), jnp.int32)), srt)
    assert float(m_r["loss"]) == pytest.approx(float(m_s["loss"]), rel=1e-5)
    np.testing.assert_allclose(
        np.asarray(s_s.tables["v"]), np.asarray(s_r.tables["v"]), rtol=1e-4, atol=1e-6
    )


@pytest.mark.parametrize("model_name", ["fm", "mvm"])
def test_stacked_sub_batches_match_single_plan(model_name):
    """NS>1 (cache-resident sub-batching) is numerically identical to
    NS=1: same logits, same one-step table update."""
    from xflow_tpu.ops.sorted_table import plan_sorted_stacked
    from xflow_tpu.optim import get_optimizer
    from xflow_tpu.train.state import TrainState
    from xflow_tpu.train.step import make_train_step

    cfg = override(Config(), **{"data.log2_slots": 12, "model.name": model_name,
                                "model.v_dim": 3, "model.num_fields": 4,
                                "data.max_nnz": 6})
    model = get_model(model_name)
    rng = np.random.default_rng(13)
    B, F = 32, 6
    slots = rng.integers(0, S, (B, F)).astype(np.int32)
    fields = rng.integers(0, 4, (B, F)).astype(np.int32)
    mask = (rng.random((B, F)) < 0.8).astype(np.float32)
    tdim = 4 if model_name == "fm" else 3
    tname = "wv" if model_name == "fm" else "v"
    tab = (rng.normal(size=(S, tdim)) * 0.1).astype(np.float32)
    base = {
        "slots": jnp.asarray(slots), "fields": jnp.asarray(fields),
        "mask": jnp.asarray(mask),
        "labels": jnp.asarray((rng.random(B) < 0.5).astype(np.float32)),
        "row_mask": jnp.ones((B,), jnp.float32),
    }
    use_fields = fields if model_name == "mvm" else None

    def arrays(ns):
        p = plan_sorted_stacked(slots, mask, S, fields=use_fields, num_sub=ns)
        out = {**base, "sorted_slots": jnp.asarray(p.sorted_slots),
               "sorted_row": jnp.asarray(p.sorted_row),
               "sorted_mask": jnp.asarray(p.sorted_mask),
               "win_off": jnp.asarray(p.win_off)}
        if use_fields is not None:
            out["sorted_fields"] = jnp.asarray(p.sorted_fields)
        return out

    a1, a4 = arrays(1), arrays(4)
    assert a4["sorted_slots"].ndim == 2 and a4["sorted_slots"].shape[0] == 4
    out1 = model.forward({tname: jnp.asarray(tab)}, a1, cfg)
    out4 = model.forward({tname: jnp.asarray(tab)}, a4, cfg)
    np.testing.assert_allclose(np.asarray(out4), np.asarray(out1), rtol=1e-5, atol=1e-7)

    opt = get_optimizer("ftrl")
    step = make_train_step(model, opt, cfg)
    s1, _ = step(TrainState({tname: jnp.asarray(tab)},
                            opt.init_state({tname: jnp.asarray(tab)}),
                            jnp.zeros((), jnp.int32)), a1)
    s4, _ = step(TrainState({tname: jnp.asarray(tab)},
                            opt.init_state({tname: jnp.asarray(tab)}),
                            jnp.zeros((), jnp.int32)), a4)
    np.testing.assert_allclose(
        np.asarray(s4.tables[tname]), np.asarray(s1.tables[tname]),
        rtol=1e-4, atol=1e-6,
    )


@pytest.mark.parametrize("standard", [True, False])
def test_fm_sorted_forward_and_step_match_rowmajor(standard):
    from xflow_tpu.optim import get_optimizer
    from xflow_tpu.train.state import TrainState
    from xflow_tpu.train.step import make_train_step

    cfg = override(Config(), **{"data.log2_slots": 12, "model.v_dim": 3,
                                "model.num_fields": 4, "data.max_nnz": 6,
                                "model.fm_standard": standard})
    assert cfg.num_slots == S
    model = get_model("fm")
    rng = np.random.default_rng(5)
    B, F = 32, 6
    slots = rng.integers(0, S, (B, F)).astype(np.int32)
    mask = (rng.random((B, F)) < 0.8).astype(np.float32)
    wv = (rng.normal(size=(S, 4)) * 0.1).astype(np.float32)
    labels = (rng.random(B) < 0.5).astype(np.float32)
    base = {
        "slots": jnp.asarray(slots),
        "fields": jnp.asarray(rng.integers(0, 4, (B, F)), jnp.int32),
        "mask": jnp.asarray(mask),
        "labels": jnp.asarray(labels),
        "row_mask": jnp.ones((B,), jnp.float32),
    }
    plan = plan_sorted_batch(slots, mask, S)
    srt = {
        **base,
        "sorted_slots": jnp.asarray(plan.sorted_slots),
        "sorted_row": jnp.asarray(plan.sorted_row),
        "sorted_mask": jnp.asarray(plan.sorted_mask),
        "win_off": jnp.asarray(plan.win_off),
    }
    out_r = model.forward({"wv": jnp.asarray(wv)}, base, cfg)
    out_s = model.forward({"wv": jnp.asarray(wv)}, srt, cfg)
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_r), rtol=1e-4, atol=1e-6)

    opt = get_optimizer("ftrl")
    t0 = {"wv": jnp.asarray(wv)}
    step = make_train_step(model, opt, cfg)
    s_r, m_r = step(TrainState(t0, opt.init_state(t0), jnp.zeros((), jnp.int32)), base)
    t1 = {"wv": jnp.asarray(wv)}
    s_s, m_s = step(TrainState(t1, opt.init_state(t1), jnp.zeros((), jnp.int32)), srt)
    assert float(m_r["loss"]) == pytest.approx(float(m_s["loss"]), rel=1e-5)
    np.testing.assert_allclose(
        np.asarray(s_s.tables["wv"]), np.asarray(s_r.tables["wv"]), rtol=1e-4, atol=1e-6
    )


def test_dot_f32_decomposition_exact_and_bf16_branches():
    """_dot_f32's 3-term split must reconstruct full-24-bit-mantissa f32
    values exactly (vs a float64 reference — a 2-term split would be
    ~2^-16 off), and the bf16 branch must show the single-pass ~2^-8
    rounding. Pure jnp — runs on CPU."""
    from xflow_tpu.ops.sorted_table import _dot_f32

    rng = np.random.default_rng(41)
    n, m = 64, 32
    # values exercising all 24 mantissa bits
    a = (rng.random((8, n)) * (1 + 2.0**-23) + rng.integers(1, 9, (8, n))).astype(np.float32)
    sel = rng.integers(0, n, m)
    onehot = np.zeros((n, m), np.float32)
    onehot[sel, np.arange(m)] = 1.0
    dims = (((1,), (0,)), ((), ()))

    want64 = a.astype(np.float64) @ onehot.astype(np.float64)  # exact selection
    got_exact = np.asarray(_dot_f32(jnp.asarray(a), jnp.asarray(onehot), dims, False))
    np.testing.assert_array_equal(got_exact.astype(np.float64), want64)

    got_bf16 = np.asarray(_dot_f32(jnp.asarray(a), jnp.asarray(onehot), dims, True))
    rel = np.abs(got_bf16.astype(np.float64) - want64) / np.abs(want64)
    assert rel.max() > 2.0**-10, "bf16 branch unexpectedly exact (not a single pass?)"
    assert rel.max() < 2.0**-7, "bf16 branch error exceeds one-pass rounding"


def test_table_gather_sorted_bf16_flag_smoke():
    """The bf16 opt-in branch keeps shapes/semantics (values bf16-rounded
    on TPU; on CPU the XLA fallback is exact either way)."""
    rng = np.random.default_rng(42)
    slots, mask, table = _random_case(rng)
    plan = plan_sorted_batch(slots, mask, S)
    occ = table_gather_sorted(
        jnp.asarray(table), jnp.asarray(plan.sorted_slots), jnp.asarray(plan.win_off),
        True,
    )
    n = slots.size
    assert occ.shape == (K8, plan.sorted_slots.shape[0])
    np.testing.assert_allclose(
        np.asarray(occ[:K, :n]).T, table[plan.sorted_slots[:n]], rtol=1e-2
    )

    def f(tab):
        o = table_gather_sorted(
            tab, jnp.asarray(plan.sorted_slots), jnp.asarray(plan.win_off), True
        )
        return (o[:K] * jnp.asarray(plan.sorted_mask)[None, :]).sum()

    g = jax.grad(f)(jnp.asarray(table))
    assert np.isfinite(np.asarray(g)).all()


def test_native_plan_rejects_out_of_range_slots():
    """An out-of-range slot must fail loudly: the radix sort masks each
    11-bit digit, so without validation a bad slot (possible only via a
    buggy caller — the parser hashes into range) would be silently
    aliased into a wrong window and its gradient scattered to a wrong
    table row (advisor r2)."""
    native = pytest.importorskip("xflow_tpu.data.native")
    try:
        native.get_lib()
    except Exception:
        pytest.skip("native library not built")
    from xflow_tpu.ops.sorted_table import padded_len

    for bad in (-1, S, S + 7):
        slots = np.zeros((4, 4), np.int32)
        slots[2, 1] = bad
        mask = np.ones((4, 4), np.float32)
        with pytest.raises(ValueError):
            native.native_plan_sorted(
                slots, mask, None, S, WINDOW, padded_len(slots.size)
            )
