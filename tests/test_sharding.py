"""Sharded-vs-single-device parity on the virtual 8-CPU-device mesh.

SURVEY.md §7 phase 3 gate: same step function, sharding specs only —
metrics must match the single-device run.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from xflow_tpu.config import Config, override
from xflow_tpu.models import get_model
from xflow_tpu.optim import get_optimizer
from xflow_tpu.parallel.mesh import make_mesh, batch_sharding
from xflow_tpu.parallel.train_step import (
    make_sharded_eval_step,
    make_sharded_train_step,
    shard_state,
)
from xflow_tpu.train import init_state, make_eval_step, make_train_step

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual CPU devices"
)


def cfg_for(model="lr", d=4, t=2, **kw):
    base = {
        "data.log2_slots": 12,
        "model.name": model,
        "model.num_fields": 5,
        "model.v_dim": 4,
        "mesh.data": d,
        "mesh.table": t,
    }
    base.update(kw)
    return override(Config(), **base)


def rand_batch(rng, B=64, F=10, num_slots=1 << 12, nf=5):
    slots = rng.integers(0, num_slots, (B, F)).astype(np.int32)
    fields = rng.integers(0, nf, (B, F)).astype(np.int32)
    mask = (rng.random((B, F)) < 0.8).astype(np.float32)
    labels = (rng.random(B) < 0.4).astype(np.float32)
    return {
        "slots": slots,
        "fields": fields,
        "mask": mask,
        "labels": labels,
        "row_mask": np.ones((B,), np.float32),
    }


@pytest.mark.parametrize("model_name", ["lr", "fm", "mvm"])
@pytest.mark.parametrize("mesh_shape", [(8, 1), (4, 2), (2, 4)])
def test_sharded_step_matches_single_device(model_name, mesh_shape):
    d, t = mesh_shape
    cfg = cfg_for(model_name, d, t)
    model, opt = get_model(model_name), get_optimizer("ftrl")
    rng = np.random.default_rng(0)
    batches = [rand_batch(rng) for _ in range(3)]

    # single-device run
    state1 = init_state(model, opt, cfg)
    step1 = make_train_step(model, opt, cfg)
    losses1 = []
    for b in batches:
        state1, m = step1(state1, {k: jnp.asarray(v) for k, v in b.items()})
        losses1.append(float(m["loss"]))

    # sharded run
    mesh = make_mesh(cfg)
    state2 = shard_state(init_state(model, opt, cfg), mesh)
    step2 = make_sharded_train_step(model, opt, cfg, mesh)
    bsh = batch_sharding(mesh)
    losses2 = []
    for b in batches:
        placed = {k: jax.device_put(jnp.asarray(v), bsh[k]) for k, v in b.items()}
        state2, m = step2(state2, placed)
        losses2.append(float(m["loss"]))

    np.testing.assert_allclose(losses1, losses2, rtol=2e-5)
    for name in state1.tables:
        np.testing.assert_allclose(
            np.asarray(state1.tables[name]),
            np.asarray(state2.tables[name]),
            rtol=2e-4,
            atol=1e-6,
        )


def test_sharded_eval_matches_single_device():
    cfg = cfg_for("fm", 4, 2)
    model = get_model("fm")
    opt = get_optimizer("ftrl")
    rng = np.random.default_rng(1)
    b = rand_batch(rng)
    state = init_state(model, opt, cfg)
    p1 = np.asarray(
        make_eval_step(model, cfg)(state.tables, {k: jnp.asarray(v) for k, v in b.items()})
    )
    mesh = make_mesh(cfg)
    sstate = shard_state(state, mesh)
    bsh = batch_sharding(mesh)
    placed = {k: jax.device_put(jnp.asarray(v), bsh[k]) for k, v in b.items()}
    p2 = np.asarray(make_sharded_eval_step(model, cfg, mesh)(sstate.tables, placed))
    np.testing.assert_allclose(p1, p2, rtol=1e-5, atol=1e-7)


def test_table_actually_sharded():
    cfg = cfg_for("lr", 4, 2)
    mesh = make_mesh(cfg)
    model, opt = get_model("lr"), get_optimizer("ftrl")
    state = shard_state(init_state(model, opt, cfg), mesh)
    w = state.tables["w"]
    # each of the 8 devices holds 1/8 of the slot axis
    shard_shapes = {s.data.shape for s in w.addressable_shards}
    assert shard_shapes == {((1 << 12) // 8,)}


def test_mesh_inference():
    cfg = override(Config(), **{"mesh.data": -1, "mesh.table": 2})
    mesh = make_mesh(cfg)
    assert mesh.shape["data"] == len(jax.devices()) // 2
    assert mesh.shape["table"] == 2
