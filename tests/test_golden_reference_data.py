"""Golden-data tests against the reference's bundled fixture
(BASELINE.md config 1). Skipped when /root/reference isn't mounted.

The reference's de-facto acceptance test (SURVEY.md §4) is a 3-shard
local run on `data/small_train-0000{0..2}` eyeballing printed
logloss/AUC. Here: train LR (and FM) on shard 0 and assert the model
separates the classes clearly better than chance, with sane logloss.
Trajectory-level parity with the async reference is not expected
(SURVEY.md §7 hard part c) — the gate is AUC-level learning on the
same bytes.
"""

import os

import numpy as np
import pytest

from xflow_tpu.config import Config, override
from xflow_tpu.train.trainer import Trainer

REF_DATA = "/root/reference/data"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF_DATA), reason="reference data not mounted"
)


def make_cfg(**kw):
    base = {
        "data.train_path": os.path.join(REF_DATA, "small_train"),
        "data.test_path": os.path.join(REF_DATA, "small_train"),  # train-set AUC: 100-line shards
        "data.log2_slots": 16,
        "data.batch_size": 10,
        "data.max_nnz": 40,
        "model.num_fields": 18,
        "train.epochs": 150,  # reference default is 60 async epochs with ~cores
        # pushes per block; sync steps need more epochs for the same optimizer-step count
        "train.pred_dump": False,
    }
    base.update(kw)
    return override(Config(), **base)


def test_lr_ftrl_learns_golden_shard():
    t = Trainer(make_cfg())
    t.fit()
    auc, ll = t.evaluate(dump=False)
    assert auc > 0.93, f"train-set auc={auc}"
    assert ll > -0.45  # mean log-likelihood in nats, well above chance (−0.693)


def test_fm_learns_golden_shard():
    t = Trainer(make_cfg(**{"model.name": "fm", "train.epochs": 60}))
    t.fit()
    auc, _ = t.evaluate(dump=False)
    assert auc > 0.85, f"train-set auc={auc}"


def test_golden_parse_shapes():
    from xflow_tpu.data.libffm import iter_examples, shard_path

    path = shard_path(os.path.join(REF_DATA, "small_train"), 0)
    examples = list(iter_examples(path, 16))
    assert len(examples) == 200
    labels = [e[0] for e in examples]
    assert set(labels) == {0.0, 1.0}
    # bundled rows carry 18 libffm field groups, up to 31 feature
    # occurrences per row (fields repeat — ordinary libffm)
    assert max(len(e[1]) for e in examples) == 31
    assert all(0 <= f < 18 for e in examples for f in e[1])
