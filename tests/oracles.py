"""Pure-NumPy oracles re-deriving the reference math for parity tests.

These intentionally re-implement, from the surveyed equations
(SURVEY.md §2 C3/C4/C11/C12), the same math as the JAX code — written
against plain dicts/loops so a bug in the framework's vectorization
can't hide in the oracle.
"""

from __future__ import annotations

import numpy as np


class FTRLOracle:
    """Per-key FTRL-proximal state machine (ftrl.h:58-74 semantics)."""

    def __init__(self, dim=(), alpha=5e-2, beta=1.0, lambda1=5e-5, lambda2=10.0):
        self.dim, self.alpha, self.beta = dim, alpha, beta
        self.lambda1, self.lambda2 = lambda1, lambda2
        self.store: dict = {}

    def _entry(self, key):
        if key not in self.store:
            z = np.zeros(self.dim) if self.dim else 0.0
            self.store[key] = {"w": np.copy(z), "n": np.copy(z), "z": np.copy(z)}
        return self.store[key]

    def push(self, key, g):
        e = self._entry(key)
        g = np.asarray(g, dtype=np.float64) if self.dim else float(g)
        old_n = e["n"]
        n = old_n + g * g
        e["z"] = e["z"] + g - (np.sqrt(n) - np.sqrt(old_n)) / self.alpha * e["w"]
        e["n"] = n
        z = e["z"]
        shrink = np.sign(z) * self.lambda1
        denom = (self.beta + np.sqrt(n)) / self.alpha + self.lambda2
        e["w"] = np.where(np.abs(z) <= self.lambda1, 0.0, -(z - shrink) / denom)

    def pull(self, key):
        return self._entry(key)["w"]


def lr_forward_oracle(w_table, rows):
    """rows: list of list-of-slot-ids. Returns logits."""
    return np.array([sum(w_table[s] for s in row) for row in rows])


def fm_forward_oracle(w_table, v_table, rows, half=True):
    """Standard FM: wx + (1/2)Σ_k[(Σ_i v)^2 − Σ_i v^2]."""
    out = []
    for row in rows:
        wx = sum(w_table[s] for s in row)
        V = np.stack([v_table[s] for s in row])  # [nnz, k]
        s = V.sum(axis=0)
        q = (V * V).sum(axis=0)
        second = (s * s - q).sum()
        if half:
            second *= 0.5
        out.append(wx + second)
    return np.array(out)


def fm_forward_reference_coupled_oracle(w_table, v_table, rows):
    """The reference's accidental cross-k form (fm_worker.cc:178-196)."""
    out = []
    for row in rows:
        wx = sum(w_table[s] for s in row)
        V = np.stack([v_table[s] for s in row])
        S = V.sum()
        Q = (V * V).sum()
        out.append(wx + S * S - Q)
    return np.array(out)


def mvm_forward_oracle(v_table, rows_slots, rows_fields, num_fields):
    """Π over present fields of per-field v sums, summed over k."""
    out = []
    for slots, fields in zip(rows_slots, rows_fields):
        V = np.stack([v_table[s] for s in slots])  # [nnz, k]
        k = V.shape[1]
        prod = np.ones(k)
        for f in range(num_fields):
            sel = [i for i, fg in enumerate(fields) if fg == f]
            if sel:
                prod = prod * V[sel].sum(axis=0)
        out.append(prod.sum())
    return np.array(out)


def sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))
