"""Packed shard cache (round 12, docs/DATA.md): format round-trip and
zero-copy reads, bitwise text/cache batch parity (padding, truncation,
feature-less rows, partial tails), writer byte-stability, the
staleness/integrity failure matrix (config mismatch, source change,
bitflip, truncation) with quarantine + text fallback, skip/resume
equivalence, the criteo_convert `cache` subcommand, trainer-integrated
cache_read attribution through metrics_report --check, the
pipeline_attrib --compare record, perf_ledger's downward
host_gap_ratio gating + text-vs-cache groups, and the
tools/smoke_cache.sh CI gate end to end."""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from xflow_tpu.config import Config, override
from xflow_tpu.data.pipeline import batch_iterator, count_batches
from xflow_tpu.data.shardcache import (
    ShardCacheDigestError,
    ShardCacheError,
    ShardCacheStale,
    build_cache,
    cache_path_for,
    open_shard_cache,
    resolve_cache,
    write_shard_cache,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))

# the report/ledger tools are exercised IN-PROCESS via their
# main(argv) -> int seams (the jax import is already paid by the test
# process; a subprocess per assertion would re-pay it ~15 times over —
# the smoke script below still drives the real CLIs end to end)
import metrics_report as mr  # noqa: E402
import perf_ledger as pl  # noqa: E402
import pipeline_attrib as pa  # noqa: E402

from xflow_tpu.tools import criteo_convert as cc  # noqa: E402


def _dcfg(**extra):
    base = {"data.log2_slots": 12, "data.max_nnz": 6, "data.batch_size": 64}
    base.update(extra)
    return override(Config(), **base).data


def _shard(tmp_path, rows=500, name="train", **gen):
    from xflow_tpu.data.synth import generate_shards

    prefix = str(tmp_path / name)
    gen.setdefault("num_fields", 4)
    gen.setdefault("ids_per_field", 50)
    gen.setdefault("seed", 0)
    generate_shards(prefix, 1, rows, **gen)
    return prefix, prefix + "-00000"


def _assert_batches_equal(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        for name in ("slots", "fields", "mask", "labels", "row_mask"):
            u, v = np.asarray(getattr(x, name)), np.asarray(getattr(y, name))
            assert u.dtype == v.dtype, name
            np.testing.assert_array_equal(u, v, err_msg=name)


# ----------------------------------------------------------- format core


def test_write_open_roundtrip_and_zero_copy(tmp_path):
    cfg = _dcfg()
    _, shard = _shard(tmp_path, rows=300)
    stats = write_shard_cache(shard, cfg)
    assert stats["rows"] == 300 and stats["bytes"] > 0
    sc = open_shard_cache(cache_path_for(shard))
    assert sc.rows == 300 and sc.max_nnz == cfg.max_nnz
    sc.verify()  # fresh file: digests hold
    # full batches are VIEWS over the file mapping, not copies — batch
    # assembly is an offset computation, the whole point of the format
    batches = list(sc.iter_batches(64))
    assert isinstance(np.asarray(batches[0].slots).base, np.memmap) or isinstance(
        batches[0].slots, np.memmap
    )
    # 300 rows / 64 = 4 full + 1 padded tail
    assert len(batches) == 5
    assert batches[-1].num_rows == 300 - 4 * 64
    assert batches[-1].batch_size == 64  # padded, like make_batch
    # drop_remainder drops exactly the tail
    assert len(list(sc.iter_batches(64, drop_remainder=True))) == 4


def test_cache_batches_bitwise_equal_text_batches(tmp_path):
    """THE parity contract (acceptance): cache-path batches are
    bitwise-identical to text-path batches on the same record set —
    labels, slots, fields, mask, row_mask, dtypes, padding included."""
    cfg = _dcfg()
    _, shard = _shard(tmp_path, rows=500)
    build_cache(str(tmp_path / "train"), cfg)
    text = list(batch_iterator(shard, dataclasses.replace(cfg, cache="off")))
    cache = list(batch_iterator(shard, dataclasses.replace(cfg, cache="on")))
    _assert_batches_equal(text, cache)
    # and under the Python parser too (both parsers emit the same
    # batches; the cache must match whichever would have run)
    pytext = list(
        batch_iterator(
            shard,
            dataclasses.replace(cfg, cache="off", use_native_parser=False),
        )
    )
    _assert_batches_equal(pytext, cache)


def test_parity_truncation_and_featureless_rows(tmp_path):
    """Rows longer than max_nnz truncate to the same deterministic
    prefix, and labeled feature-less rows (the bad-record class) are
    PRESERVED as masked-empty rows — the quarantine/budget machinery
    must see the same rows on both paths."""
    shard = tmp_path / "t-00000"
    shard.write_text(
        "1\t0:a:1 1:b:1 2:c:1 3:d:1 4:e:1\n"  # 5 features > max_nnz=3
        "0\tgarbage novalue\n"  # labeled, zero parseable features
        "1\t2:x:1\n"
        "junk_line_without_separator\n"
        "0\t0:a:1 1:b:1\n"
    )
    cfg = _dcfg(**{"data.max_nnz": 3, "data.batch_size": 2})
    write_shard_cache(str(shard), cfg)
    text = list(
        batch_iterator(
            str(shard), dataclasses.replace(cfg, cache="off"),
            enforce_bad_rows=False,
        )
    )
    cache = list(
        batch_iterator(
            str(shard), dataclasses.replace(cfg, cache="on"),
            enforce_bad_rows=False,
        )
    )
    _assert_batches_equal(text, cache)
    # the truncated row kept its first 3 features; the bad row is there
    assert text[0].mask[0].sum() == 3
    assert text[0].row_mask[1] == 1.0 and text[0].mask[1].sum() == 0


def test_quarantine_parity_on_cache_path(tmp_path):
    """Bad feature-less rows quarantine IDENTICALLY from cache batches:
    the monitor is batch-level and parser-agnostic by construction, and
    the cache preserves the rows (docs/ROBUSTNESS.md)."""
    from xflow_tpu.jsonl import read_jsonl

    shard = tmp_path / "t-00000"
    shard.write_text("1\t0:a:1\n0\tjunk novalue\n1\t1:b:1\n")
    cfg = _dcfg(**{"data.batch_size": 2})
    write_shard_cache(str(shard), cfg)
    qs = {}
    for mode in ("off", "on"):
        qp = str(tmp_path / f"q_{mode}.jsonl")
        c = dataclasses.replace(cfg, cache=mode, quarantine_path=qp)
        list(batch_iterator(str(shard), c, enforce_bad_rows=False))
        qs[mode] = [
            {k: r[k] for k in ("source", "batch", "row", "label")}
            for r in read_jsonl(qp)
        ]
    assert qs["off"] == qs["on"] and len(qs["on"]) == 1


def test_writer_byte_stable(tmp_path):
    """Two builds of the same input are byte-identical — no timestamps,
    no run-local values; determinism is what makes the digests mean
    'corruption' and not 'rebuilt'."""
    cfg = _dcfg()
    prefix, shard = _shard(tmp_path, rows=200)
    build_cache(prefix, cfg)
    h1 = hashlib.sha256(open(cache_path_for(shard), "rb").read()).hexdigest()
    build_cache(prefix, cfg, force=True)
    h2 = hashlib.sha256(open(cache_path_for(shard), "rb").read()).hexdigest()
    assert h1 == h2


def test_skip_batches_equivalence(tmp_path):
    """`skip` (the data_state resume seam) lands on the same batch
    boundary on both paths — PR-4 elastic resume works unchanged on
    cache shards."""
    cfg = _dcfg()
    prefix, shard = _shard(tmp_path, rows=400)
    build_cache(prefix, cfg)
    for skip in (0, 3, 6):
        text = list(
            batch_iterator(shard, dataclasses.replace(cfg, cache="off"), skip=skip)
        )
        cache = list(
            batch_iterator(shard, dataclasses.replace(cfg, cache="on"), skip=skip)
        )
        _assert_batches_equal(text, cache)
    assert len(text) == count_batches(shard, cfg) - 6


def test_cache_dir_layout(tmp_path):
    cfg = _dcfg(**{"data.cache_dir": str(tmp_path / "cachedir")})
    prefix, shard = _shard(tmp_path, rows=100)
    build_cache(prefix, cfg)
    cpath = cache_path_for(shard, cfg.cache_dir)
    assert os.path.dirname(cpath) == str(tmp_path / "cachedir")
    assert os.path.exists(cpath)
    assert not os.path.exists(shard + ".xfc")
    cache = list(batch_iterator(shard, dataclasses.replace(cfg, cache="on")))
    text = list(batch_iterator(shard, dataclasses.replace(cfg, cache="off")))
    _assert_batches_equal(text, cache)


def test_cache_dir_keys_datasets_apart(tmp_path):
    """Regression (review round): two datasets with identically-named
    shards sharing one data.cache_dir must get DISTINCT cache files —
    basename-only keying would let them clobber each other (or, at
    equal byte sizes, silently serve the other dataset's rows)."""
    cfg = _dcfg(**{"data.cache_dir": str(tmp_path / "shared")})
    (tmp_path / "a").mkdir()
    (tmp_path / "b").mkdir()
    pa, shard_a = _shard(tmp_path / "a", rows=100, seed=1)
    pb, shard_b = _shard(tmp_path / "b", rows=100, seed=2)
    build_cache(pa, cfg)
    build_cache(pb, cfg)
    ca, cb = cache_path_for(shard_a, cfg.cache_dir), cache_path_for(
        shard_b, cfg.cache_dir
    )
    assert ca != cb and os.path.exists(ca) and os.path.exists(cb)
    # and each serves ITS OWN rows
    for shard in (shard_a, shard_b):
        _assert_batches_equal(
            list(batch_iterator(shard, dataclasses.replace(cfg, cache="off"))),
            list(batch_iterator(shard, dataclasses.replace(cfg, cache="on"))),
        )


def test_build_cache_repairs_corrupt_cache_without_force(tmp_path):
    """Regression (review round): an explicit `criteo_convert cache`
    build is the operator's REPAIR path — a corrupt-but-config-fresh
    cache must be rebuilt, not reported as skipped."""
    cfg = _dcfg()
    prefix, shard = _shard(tmp_path, rows=150)
    build_cache(prefix, cfg)
    cpath = cache_path_for(shard)
    with open(cpath, "r+b") as f:
        f.seek(80)
        b = f.read(1)
        f.seek(80)
        f.write(bytes([b[0] ^ 0xFF]))
    stats = build_cache(prefix, cfg)  # no --force needed
    assert stats["shards"] == 1 and stats["skipped"] == 0
    open_shard_cache(cpath).verify()  # repaired


# ------------------------------------------------------- failure matrix


def test_stale_config_mismatch(tmp_path):
    cfg = _dcfg()
    prefix, shard = _shard(tmp_path, rows=100)
    build_cache(prefix, cfg)
    other = dataclasses.replace(cfg, log2_slots=13)
    # auto: stale cache is skipped (warn + text path)
    assert resolve_cache(shard, dataclasses.replace(other, cache="auto")) is None
    # on: the operator asserted cached input — stale raises loudly
    with pytest.raises(ShardCacheStale, match="log2_slots"):
        resolve_cache(shard, dataclasses.replace(other, cache="on"))
    for field in ("hash_salt", "max_nnz"):
        bad = dataclasses.replace(cfg, cache="on", **{field: 7})
        with pytest.raises(ShardCacheStale, match=field):
            resolve_cache(shard, bad)


def test_stale_cache_on_mode_raises_through_batch_iterator(tmp_path):
    """Regression (review round): ShardCacheStale subclasses
    ShardCacheError, and the pipeline's corruption net must NOT swallow
    it — under data.cache=on a stale cache raises loudly THROUGH
    batch_iterator (a silent text fallback would re-measure the very
    path the operator forced the cache to replace), with no bogus
    quarantine record."""
    from xflow_tpu.jsonl import read_jsonl

    cfg = _dcfg()
    prefix, shard = _shard(tmp_path, rows=100)
    build_cache(prefix, cfg)
    qp = str(tmp_path / "q.jsonl")
    stale_on = dataclasses.replace(
        cfg, cache="on", log2_slots=13, quarantine_path=qp
    )
    with pytest.raises(ShardCacheStale, match="log2_slots"):
        list(batch_iterator(shard, stale_on))
    assert not os.path.exists(qp) or not read_jsonl(qp)


def test_corrupt_footer_geometry_quarantined_not_crashed(tmp_path):
    """Regression (review round): the crc32 digests cover section
    bytes, not the footer — a flipped shape/offset digit must be a
    ShardCacheError at open (→ quarantine + text fallback), never a
    bare np.memmap ValueError inside the prefetch thread."""
    from xflow_tpu.jsonl import read_jsonl

    cfg = _dcfg()
    prefix, shard = _shard(tmp_path, rows=200)
    build_cache(prefix, cfg)
    cpath = cache_path_for(shard)
    blob = bytearray(open(cpath, "rb").read())
    # inflate the slots section's row count in the footer JSON: ASCII
    # '2' -> ':'? keep it a digit — '2' -> '9' keeps valid JSON and a
    # shape far past the file end
    footer_start = blob.rfind(b'"rows":200')
    assert footer_start > 0
    blob[footer_start + len(b'"rows":') : footer_start + len(b'"rows":2')] = b"9"
    open(cpath, "wb").write(bytes(blob))
    with pytest.raises(ShardCacheError):
        open_shard_cache(cpath)
    text = list(batch_iterator(shard, dataclasses.replace(cfg, cache="off")))
    qp = str(tmp_path / "q.jsonl")
    got = list(
        batch_iterator(shard, dataclasses.replace(cfg, quarantine_path=qp))
    )
    _assert_batches_equal(text, got)
    assert read_jsonl(qp)[0]["reason"] == "cache_unreadable"


def test_stale_source_changed(tmp_path):
    cfg = _dcfg()
    prefix, shard = _shard(tmp_path, rows=100)
    build_cache(prefix, cfg)
    with open(shard, "a") as f:
        f.write("1\t0:zzz:1\n")  # the text shard grew: cache is stale
    assert resolve_cache(shard, cfg) is None
    with pytest.raises(ShardCacheStale, match="changed"):
        resolve_cache(shard, dataclasses.replace(cfg, cache="on"))
    # and batch_iterator transparently serves the GROWN file from text
    got = list(batch_iterator(shard, cfg))
    assert sum(b.num_rows for b in got) == 101


def test_missing_cache_on_mode_raises(tmp_path):
    cfg = dataclasses.replace(_dcfg(), cache="on")
    _, shard = _shard(tmp_path, rows=50)
    with pytest.raises(FileNotFoundError, match="criteo_convert cache"):
        list(batch_iterator(shard, cfg))
    # auto: no cache is simply the text path
    got = list(batch_iterator(shard, dataclasses.replace(cfg, cache="auto")))
    assert sum(b.num_rows for b in got) == 50


def test_bitflip_detected_named_and_fallen_back(tmp_path):
    """The integrity acceptance: one flipped payload byte is caught by
    the section digest, the quarantine record NAMES the section, the
    counter ticks, and the stream falls back to text — bitwise-equal
    output, zero failures, even under data.cache=on."""
    from xflow_tpu.jsonl import read_jsonl
    from xflow_tpu.telemetry import default_registry

    cfg = _dcfg()
    prefix, shard = _shard(tmp_path, rows=300)
    build_cache(prefix, cfg)
    text = list(batch_iterator(shard, dataclasses.replace(cfg, cache="off")))
    cpath = cache_path_for(shard)
    with open(cpath, "r+b") as f:
        f.seek(100)  # inside the slots section (starts at 64)
        b = f.read(1)
        f.seek(100)
        f.write(bytes([b[0] ^ 0x01]))
    with pytest.raises(ShardCacheDigestError, match="slots") as ei:
        open_shard_cache(cpath).verify()
    assert ei.value.section == "slots"
    default_registry().reset()
    qp = str(tmp_path / "q.jsonl")
    run_cfg = dataclasses.replace(cfg, cache="on", quarantine_path=qp)
    got = list(batch_iterator(shard, run_cfg))
    _assert_batches_equal(text, got)
    q = read_jsonl(qp)
    assert q and q[0]["reason"] == "cache_digest_mismatch"
    assert q[0]["section"] == "slots" and q[0]["cache"] == cpath
    snap = default_registry().snapshot()
    assert snap.get("data.cache_fallbacks") == 1


def test_truncated_and_garbage_cache_files_fall_back(tmp_path):
    from xflow_tpu.jsonl import read_jsonl

    cfg = _dcfg()
    prefix, shard = _shard(tmp_path, rows=200)
    build_cache(prefix, cfg)
    cpath = cache_path_for(shard)
    blob = open(cpath, "rb").read()
    text = list(batch_iterator(shard, dataclasses.replace(cfg, cache="off")))
    qp = str(tmp_path / "q.jsonl")
    run_cfg = dataclasses.replace(cfg, quarantine_path=qp)
    for label, payload in (
        ("truncated", blob[: len(blob) // 2]),
        ("garbage", b"not a cache file at all"),
        ("bad_magic", b"XXXX" + blob[4:]),
    ):
        open(cpath, "wb").write(payload)
        with pytest.raises(ShardCacheError):
            open_shard_cache(cpath).verify()
        got = list(batch_iterator(shard, run_cfg))
        _assert_batches_equal(text, got)
    reasons = {r["reason"] for r in read_jsonl(qp)}
    assert reasons == {"cache_unreadable"}


def test_future_version_rejected(tmp_path):
    import struct

    cfg = _dcfg()
    prefix, shard = _shard(tmp_path, rows=50)
    build_cache(prefix, cfg)
    cpath = cache_path_for(shard)
    with open(cpath, "r+b") as f:
        f.seek(4)
        f.write(struct.pack("<I", 99))
    with pytest.raises(ShardCacheError, match="v99"):
        open_shard_cache(cpath)


def test_invalid_cache_mode_rejected(tmp_path):
    from xflow_tpu.train.trainer import Trainer

    cfg = override(Config(), **{"data.cache": "maybe"})
    with pytest.raises(ValueError, match="auto|on|off"):
        Trainer(cfg)
    _, shard = _shard(tmp_path, rows=50)
    with pytest.raises(ValueError, match="auto|on|off"):
        list(batch_iterator(shard, _dcfg(**{"data.cache": "sometimes"})))


# -------------------------------------------------------- converter CLI


def test_criteo_convert_cache_subcommand(tmp_path, capsys):
    _shard(tmp_path, rows=120)
    args = ["cache", str(tmp_path / "train"),
            "--log2-slots", "12", "--max-nnz", "6"]
    assert cc.main(args) == 0
    stats = json.loads(capsys.readouterr().out)
    assert stats == {"shards": 1, "rows": 120,
                     "bytes": stats["bytes"], "skipped": 0}
    assert os.path.exists(str(tmp_path / "train-00000.xfc"))
    # incremental: a fresh cache is skipped; --force rebuilds
    assert cc.main(args) == 0
    assert json.loads(capsys.readouterr().out)["skipped"] == 1
    assert cc.main(args + ["--force"]) == 0
    assert json.loads(capsys.readouterr().out)["shards"] == 1
    # no shards at all is a loud error
    with pytest.raises(FileNotFoundError):
        cc.main(["cache", str(tmp_path / "nope")])


def test_criteo_convert_one_pass_with_cache_flag(tmp_path, capsys):
    """raw TSV -> libffm shards -> .xfc caches in ONE invocation
    (--cache): 'hash at convert time' end to end."""
    rng = np.random.default_rng(0)
    from tests.test_criteo_convert import _raw_criteo_rows

    raw = tmp_path / "raw.tsv"
    raw.write_text("".join(_raw_criteo_rows(rng, 80)))
    assert cc.main([str(raw), str(tmp_path / "c"), "--shards", "2",
                    "--cache", "--log2-slots", "14", "--max-nnz", "39"]) == 0
    stats = json.loads(capsys.readouterr().out)
    assert stats["rows"] == 80 and stats["cache"]["shards"] == 2
    assert stats["cache"]["rows"] == 80
    for s in range(2):
        sc = open_shard_cache(str(tmp_path / f"c-{s:05d}.xfc"))
        sc.verify()
        assert sc.rows == 40


# ------------------------------------------------- trainer + telemetry


def test_trainer_cached_run_attributes_cache_read(tmp_path):
    """A profiled cached run emits cache_read_s > 0 with parse/read/hash
    at 0, passes the --check pipeline gate, and trains the same example
    count as the text run — the cache_read stage satellite end to end."""
    from xflow_tpu.jsonl import read_jsonl
    from xflow_tpu.train.trainer import Trainer

    prefix, shard = _shard(tmp_path, rows=320, num_fields=6)
    base = {
        "model.name": "lr", "data.train_path": prefix,
        "data.log2_slots": 12, "data.max_nnz": 8, "data.batch_size": 64,
        "model.num_fields": 6, "train.epochs": 1, "train.pred_dump": False,
        "train.log_every": 2, "train.pipeline_metrics": True,
    }
    cfg = override(Config(), **base)
    build_cache(prefix, cfg.data)
    cfg = override(cfg, **{
        "data.cache": "on",
        "train.metrics_path": str(tmp_path / "run" / "metrics_rank0.jsonl"),
    })
    from xflow_tpu.telemetry import default_registry

    default_registry().reset()  # counters are process-global
    res = Trainer(cfg).fit()
    assert res.steps == 5 and res.examples == 320
    recs = read_jsonl(str(tmp_path / "run" / "metrics_rank0.jsonl"))
    pipe = [r for r in recs if r.get("kind") == "pipeline"]
    assert pipe
    assert sum(r["cache_read_s"] for r in pipe) > 0
    for stage in ("read", "parse", "hash", "batch", "pad"):
        assert sum(r[f"{stage}_s"] for r in pipe) == 0.0, stage
    assert sum(r["rows"] for r in pipe) == 320
    # counters carry the cache provenance
    finals = [r for r in recs if r.get("final")]
    assert finals[0]["counters"].get("data.cache_shards") == 1
    assert mr.main([str(tmp_path / "run"), "--check"]) == 0


def test_pipeline_verdict_names_cache_bound_producer():
    from xflow_tpu.telemetry import pipeline_verdict

    v = pipeline_verdict({"queue_wait": 6.0, "cache_read": 7.0, "parse": 0.1},
                         10.0)
    assert v.startswith("host-bound in cache_read: 70%")


def test_metrics_report_tolerates_pre_cache_archives(tmp_path, capsys):
    """A kind="pipeline" record WITHOUT cache_read_s (a pre-round-12
    archive) still passes --check: the key is optional-for-archives,
    required in spirit for new writers (OPTIONAL_PIPELINE_KEYS)."""
    rec = {"ts": 1.0, "rank": 0, "run_id": "r", "gen": 0,
           "kind": "pipeline", "step": 10}
    for key in mr.PIPELINE_KEYS:
        rec.setdefault(key, 0.001)
    rec["wall_s"] = 1.0
    del rec["cache_read_s"]
    (tmp_path / "m.jsonl").write_text(json.dumps(rec) + "\n")
    assert mr.main([str(tmp_path / "m.jsonl"), "--check"]) == 0, (
        capsys.readouterr().err
    )
    # but a record missing a NON-optional key still fails
    del rec["parse_s"]
    (tmp_path / "m.jsonl").write_text(json.dumps(rec) + "\n")
    assert mr.main([str(tmp_path / "m.jsonl"), "--check"]) == 2
    capsys.readouterr()
    # and cache_read_s, when present, joins the producer sum gate
    rec["parse_s"] = 0.001
    rec["cache_read_s"] = 3.0
    (tmp_path / "m.jsonl").write_text(json.dumps(rec) + "\n")
    assert mr.main([str(tmp_path / "m.jsonl"), "--check"]) == 2
    assert "producer-side stage times sum" in capsys.readouterr().err


# --------------------------------------------------- attrib + ledger


def _pipe_bench(value, ratio, rnd, **extra):
    return {"metric": "pipeline_e2e_examples_per_sec", "value": value,
            "unit": "examples/sec", "round": rnd,
            "device_bound_examples_per_sec": value * ratio,
            "host_gap_ratio": ratio, **extra}


def test_pipeline_attrib_compare_folds_text_leg(tmp_path, capsys):
    (tmp_path / "text.json").write_text(json.dumps(_pipe_bench(5000.0, 8.0, 12)))
    m = [{"ts": float(i), "rank": 0, "run_id": "r", "gen": 0, "step": i * 2,
          "examples": i * 1000, "elapsed_s": i * 0.02, "loss": 0.5}
         for i in range(1, 4)]
    p = {"ts": 5.0, "rank": 0, "run_id": "r", "gen": 0, "kind": "pipeline",
         "step": 6, "wall_s": 0.06, "batches": 3, "rows": 3000,
         "queue_depth": 1, "queue_cap": 2}
    for key in mr.PIPELINE_KEYS:
        p.setdefault(key, 0.001)
    (tmp_path / "m.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in m + [p])
    )
    out = tmp_path / "BENCH.json"
    assert pa.main([str(tmp_path / "m.jsonl"), "--bench-json", str(out),
                    "--round", "12",
                    "--compare", str(tmp_path / "text.json")]) == 0
    assert "vs text:" in capsys.readouterr().out
    rec = json.loads(out.read_text())
    assert rec["text_e2e_examples_per_sec"] == 5000.0
    assert rec["text_host_gap_ratio"] == 8.0
    assert rec["speedup_vs_text"] == pytest.approx(
        rec["value"] / 5000.0, abs=1e-3
    )
    # a bad comparison file is a loud exit 2, not a silent record
    assert pa.main([str(tmp_path / "m.jsonl"), "--bench-json", str(out),
                    "--compare", str(tmp_path / "nope.json")]) == 2


def test_perf_ledger_host_gap_ratio_gates_downward(tmp_path, capsys):
    # r11: text path, e2e 4000 at gap 2.0 (device-bound 8000); r12: the
    # cache round, e2e 40000 at gap 1.1 (device-bound 44000) — every
    # throughput group rises, the ratio falls: the healthy trajectory
    (tmp_path / "BENCH_PIPELINE_r11.json").write_text(
        json.dumps(_pipe_bench(4000.0, 2.0, 11)))
    (tmp_path / "BENCH_PIPELINE_r12.json").write_text(
        json.dumps(_pipe_bench(40000.0, 1.1, 12,
                               text_e2e_examples_per_sec=4000.0,
                               speedup_vs_text=10.0)))
    out = tmp_path / "ledger.json"
    assert pl.main(["--root", str(tmp_path), "--json", str(out),
                    "--regress", "--markdown", ""]) == 0, (
        capsys.readouterr().err
    )  # the gap CLOSED: no regression
    entries = json.loads(out.read_text())["entries"]
    metrics = {e["metric"] for e in entries}
    assert {"pipeline_host_gap_ratio", "pipeline_speedup_vs_text",
            "text_e2e_examples_per_sec",
            "device_bound_examples_per_sec"} <= metrics
    ratio = [e for e in entries if e["metric"] == "pipeline_host_gap_ratio"]
    assert [e["value"] for e in ratio] == [2.0, 1.1]
    # a later round whose ratio climbs back toward text-path numbers
    # is a REGRESSION (exit 3) even though its e2e did not drop
    (tmp_path / "BENCH_PIPELINE_r13.json").write_text(
        json.dumps(_pipe_bench(40000.0, 6.0, 13)))
    capsys.readouterr()
    assert pl.main(["--root", str(tmp_path), "--regress",
                    "--markdown", ""]) == 3
    assert "pipeline_host_gap_ratio" in capsys.readouterr().err


def test_perf_ledger_renders_pipeline_section(tmp_path, capsys):
    (tmp_path / "BENCH_PIPELINE_r12.json").write_text(
        json.dumps(_pipe_bench(40000.0, 1.1, 12,
                               text_e2e_examples_per_sec=4000.0,
                               speedup_vs_text=10.0)))
    assert pl.main(["--root", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "Input pipeline" in out
    assert "pipeline_speedup_vs_text" in out


# ------------------------------------------------------------ smoke gate


@pytest.mark.slow
def test_smoke_cache_script(tmp_path):
    """The packed-shard-cache CI gate end to end (tools/smoke_cache.sh):
    convert -> cache -> text-vs-cache profiled runs -> >= 5x + >= 95%
    attribution -> bitwise parity -> kill/resume accounting -> bitflip
    quarantine drill -> ledger fold + downward-gating mechanics.

    slow-marked: the text leg alone is ~10s of single-core Python
    parsing by design (it IS the host gap being measured), and the
    tier-1 sweep sits within seconds of its timeout budget — run this
    via `pytest -m slow tests/test_shardcache.py` or
    `bash tools/smoke_cache.sh` (the standalone form also records the
    committed round-12 datapoint). Every individual contract the smoke
    composes — parity, resume-skip equivalence, bitflip quarantine +
    fallback, converter CLI, attrib --compare, ledger gating — is
    ALSO covered by the fast in-process tests above, so tier-1 still
    gates the subsystem; this drill proves the composed CLI path."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        ["bash", os.path.join(REPO_ROOT, "tools", "smoke_cache.sh"),
         str(tmp_path)],
        capture_output=True, text=True, env=env, timeout=600,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "smoke_cache: OK" in r.stdout
    # the round-12 datapoint stayed in the workdir (never the repo root
    # from a test run) and carries both legs
    rec = json.loads((tmp_path / "BENCH_PIPELINE_r12.json").read_text())
    assert rec["round"] == 12
    assert rec["speedup_vs_text"] >= 5.0
    assert (tmp_path / "ledger.md").exists()
