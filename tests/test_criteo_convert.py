"""Real-dataset ingestion recipe (docs/DATASETS.md): the Criteo/Avazu
raw-TSV → libffm converter, smoke-tested END-TO-END — synthetic raw
fixture → convert → the real parser/trainer — so the only unexercised
step on a real mount is the download (round-3 verdict missing #5)."""

import json
import subprocess
import sys

import numpy as np
import pytest

from xflow_tpu.tools.criteo_convert import (
    N_CAT,
    N_INT,
    avazu_line_to_libffm,
    convert,
    criteo_line_to_libffm,
)


def _raw_criteo_rows(rng, n):
    for i in range(n):
        ints = [
            "" if rng.random() < 0.2 else str(int(rng.integers(-2, 10_000)))
            for _ in range(N_INT)
        ]
        cats = [
            "" if rng.random() < 0.1 else format(int(rng.integers(0, 1 << 32)), "08x")
            for _ in range(N_CAT)
        ]
        yield "\t".join([str(i % 2)] + ints + cats) + "\n"


def test_criteo_line_transform():
    line = "1\t" + "\t".join(["3"] + [""] * 11 + ["-5"]) + "\t" + "\t".join(
        ["68fd1e64"] + [""] * 25
    )
    out = criteo_line_to_libffm(line + "\n")
    # I1=3 -> bucket log2(4)=2; I13=-5 -> NEG; C1 verbatim — each with
    # the FIELD FOLDED INTO THE TOKEN (the framework hashes only the
    # feature text, so un-prefixed tokens would alias across fields)
    assert out == "1\t0:I0_2:1 12:I12_NEG:1 13:C13_68fd1e64:1"
    assert criteo_line_to_libffm("2\t" + "\t".join([""] * (N_INT + N_CAT))) is None
    assert criteo_line_to_libffm("bad line") is None


def test_criteo_tokens_do_not_alias_across_fields():
    """Value 3 in field I1 and field I2 must produce DIFFERENT feature
    tokens — same-value aliasing across fields would collapse all 13
    integer fields onto ~41 shared weights."""
    line = "0\t3\t3" + "\t" * (N_INT - 2 + N_CAT)
    out = criteo_line_to_libffm(line)
    t0, t1 = out.split("\t")[1].split(" ")
    assert t0.split(":")[1] != t1.split(":")[1], (t0, t1)


def test_avazu_line_transform():
    assert (
        avazu_line_to_libffm("id123,1,14102100,aa,bb\n", 3)
        == "1\t0:A0_14102100:1 1:A1_aa:1 2:A2_bb:1"
    )
    # same value in two columns -> distinct tokens
    out = avazu_line_to_libffm("id,0,1,1\n", 2)
    toks = [t.split(":")[1] for t in out.split("\t")[1].split(" ")]
    assert toks[0] != toks[1]
    assert avazu_line_to_libffm("id123,2,x,y,z\n", 3) is None


def test_convert_and_train_end_to_end(tmp_path):
    """Fixture raw TSV → converter → shards → the REAL trainer (native
    parser, sorted engine) — the docs/DATASETS.md recipe minus the
    download."""
    rng = np.random.default_rng(0)
    raw = tmp_path / "raw.tsv"
    raw.write_text("".join(_raw_criteo_rows(rng, 600)))

    r = subprocess.run(
        [sys.executable, "-m", "xflow_tpu.tools.criteo_convert",
         str(raw), str(tmp_path / "train"), "--shards", "2"],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stderr
    stats = json.loads(r.stdout)
    assert stats["rows"] == 600 and stats["skipped"] == 0
    assert stats["fields"] == N_INT + N_CAT

    # both shards exist, rows split round-robin
    lines0 = (tmp_path / "train-00000").read_text().strip().split("\n")
    lines1 = (tmp_path / "train-00001").read_text().strip().split("\n")
    assert len(lines0) == len(lines1) == 300
    label, first_tok = lines0[0].split("\t")[0], lines0[0].split("\t")[1].split(" ")[0]
    assert label in "01" and first_tok.count(":") == 2

    from xflow_tpu.config import Config, override
    from xflow_tpu.train.trainer import Trainer

    cfg = override(Config(), **{
        "data.train_path": str(tmp_path / "train"),
        "data.log2_slots": 16,
        "data.batch_size": 64,
        "data.max_nnz": N_INT + N_CAT,
        "model.name": "fm",
        "model.num_fields": N_INT + N_CAT,
        "train.epochs": 1,
        "train.pred_dump": False,
    })
    res = Trainer(cfg).fit()
    assert res.steps == 300 // 64 + 1  # shard 0's 300 rows, last padded
    assert np.isfinite(res.last_loss)


def test_convert_stdin_and_limit(tmp_path):
    rng = np.random.default_rng(1)
    raw = "".join(_raw_criteo_rows(rng, 50))
    r = subprocess.run(
        [sys.executable, "-m", "xflow_tpu.tools.criteo_convert",
         "-", str(tmp_path / "t"), "--shards", "1", "--limit", "20"],
        input=raw, capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout)["rows"] == 20


def test_convert_avazu(tmp_path):
    raw = tmp_path / "a.csv"
    raw.write_text(
        "id,click,hour,C1,banner_pos\n"
        "1000,0,14102100,1005,0\n"
        "1001,1,14102101,1002,1\n"
    )
    stats = convert(open(raw), str(tmp_path / "av"), 1, fmt="avazu")
    assert stats == {"rows": 2, "skipped": 0, "fields": 3}
    lines = (tmp_path / "av-00000").read_text().strip().split("\n")
    assert lines[1] == "1\t0:A0_14102101:1 1:A1_1002:1 2:A2_1:1"


def test_convert_avazu_no_header(tmp_path):
    """Headerless chunks (tail/split pieces): the first line is DATA and
    must be converted, not silently swallowed."""
    raw = tmp_path / "chunk.csv"
    raw.write_text(
        "1000,0,14102100,1005,0\n"
        "1001,1,14102101,1002,1\n"
    )
    stats = convert(open(raw), str(tmp_path / "av"), 1, fmt="avazu",
                    header=False)
    assert stats == {"rows": 2, "skipped": 0, "fields": 3}
    first = (tmp_path / "av-00000").read_text().strip().split("\n")[0]
    assert first.startswith("0\t0:A0_14102100:1")


def test_dirty_categorical_values_escaped_not_mistokenized():
    """A categorical value containing libffm structural characters
    (whitespace, ':', '%') must emit a WELL-FORMED token — escaped
    injectively, so distinct dirty values stay distinct (round-4
    ADVICE: unsanitized interpolation mis-tokenized downstream)."""
    from xflow_tpu.tools.criteo_convert import _sanitize, avazu_line_to_libffm

    dirty = ["a b", "a:b", "a%3Ab", "a\tb", "a%b"]
    sanitized = [_sanitize(v) for v in dirty]
    # injective and structurally clean
    assert len(set(sanitized)) == len(dirty)
    for s in sanitized:
        assert not any(c in s for c in " \t:"), s
    # a clean value can never collide with an escaped one ('%' escaped)
    assert _sanitize("a%3Ab") != "a%3Ab"
    assert _sanitize("clean") == "clean"
    # through the real converters: every token still parses 3-way
    line = "0\t" + "\t".join([""] * N_INT) + "\t" + "\t".join(
        ["has space", "has:colon"] + [""] * (N_CAT - 2)
    )
    out = criteo_line_to_libffm(line)
    toks = out.split("\t")[1].split(" ")
    assert len(toks) == 2
    for t in toks:
        assert len(t.split(":")) == 3, t
    av = avazu_line_to_libffm("id,1,x y,w:z\n", 2)
    for t in av.split("\t")[1].split(" "):
        assert len(t.split(":")) == 3, t


def test_convert_output_byte_stable_across_runs(tmp_path):
    """Two conversions of the same input must produce BYTE-IDENTICAL
    shard files — determinism is what makes the shard cache's crc32
    digests meaningful (docs/DATA.md): a converter that stamped
    timestamps, iteration order, or any run-local value into its
    output would make every rebuilt cache look corrupted. Covers both
    formats and the stdin path (same code path, one file fixture)."""
    rng = np.random.default_rng(7)
    raw = tmp_path / "raw.tsv"
    # include a dirty categorical value so the escape path is pinned too
    rows = list(_raw_criteo_rows(rng, 200))
    rows[3] = "\t".join(["1"] + ["3"] * N_INT + ["a b:c%"] * N_CAT) + "\n"
    raw.write_text("".join(rows))

    def run(out):
        with open(raw) as src:
            stats = convert(src, str(out), 2)
        assert stats["skipped"] <= 1  # the dirty row still converts
        return [
            (tmp_path / f"{out.name}-{s:05d}").read_bytes() for s in range(2)
        ]

    first = run(tmp_path / "a")
    second = run(tmp_path / "b")
    assert first == second, "criteo converter output is not byte-stable"

    av = tmp_path / "a.csv"
    av.write_text("id,click,h,c\n" + "".join(
        f"i{k},{k % 2},{k},v{k}\n" for k in range(50)
    ))
    convert(open(av), str(tmp_path / "av1"), 1, fmt="avazu")
    convert(open(av), str(tmp_path / "av2"), 1, fmt="avazu")
    assert (tmp_path / "av1-00000").read_bytes() == (
        tmp_path / "av2-00000"
    ).read_bytes(), "avazu converter output is not byte-stable"


def test_convert_shard_count_beyond_fd_limit_raises_early(tmp_path):
    """--shards beyond the process fd budget must fail with the clear
    up-front error, not EMFILE mid-stream (round-4 ADVICE)."""
    import resource

    soft = resource.getrlimit(resource.RLIMIT_NOFILE)[0]
    if soft == resource.RLIM_INFINITY or soft > 1 << 20:
        pytest.skip("no practical fd limit on this host")
    with pytest.raises(ValueError, match="fd limit"):
        convert(iter([]), str(tmp_path / "x"), int(soft), fmt="avazu")
