"""Fault-injection suite: every recovery path of the resilience
subsystem (docs/ROBUSTNESS.md) driven end-to-end through the shared
injector library (xflow_tpu/testing/faults.py).

Covers the failure matrix: NaN-poisoned batch (non-finite guard skip /
halt / consecutive-abort / off), truncated and bit-flipped npz + orbax
checkpoints (self-healing restore walk-back), malformed libffm shards
(bad-record quarantine + budget), a killed rank under launch-dist
(committed checkpoint survives and restores), plus the lifecycle
satellites (MetricsLogger close, prefetch worker exit, stale-dir
cleanup, retention).
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from xflow_tpu.config import Config, override
from xflow_tpu.data.synth import generate_shards
from xflow_tpu.testing.faults import (
    bitflip_file,
    corrupt_npz_checkpoint,
    corrupt_orbax_checkpoint,
    poison_nan_batches,
    truncate_file,
    write_malformed_libffm,
)
from xflow_tpu.train.checkpoint import committed_steps, orbax_steps
from xflow_tpu.train.trainer import NonFiniteHalt, Trainer

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_cfg(tmp_path, **kw):
    base = {
        "data.train_path": str(tmp_path / "train"),
        "data.log2_slots": 12,
        "data.batch_size": 100,
        "data.max_nnz": 8,
        "model.num_fields": 5,
        "train.epochs": 2,
        "train.log_every": 1,
        "train.pred_dump": False,
    }
    base.update(kw)
    return override(Config(), **base)


@pytest.fixture
def dataset(tmp_path):
    generate_shards(
        str(tmp_path / "train"), 1, 600, num_fields=5, ids_per_field=30, seed=0
    )
    return tmp_path


# ---------------------------------------------------------- non-finite guard
def test_nan_batch_skipped_run_completes(dataset, tmp_path):
    """Acceptance: a NaN-poisoned batch under nonfinite_guard=skip is
    discarded, counted in the metrics JSONL, and the run's final loss is
    finite."""
    mpath = tmp_path / "m" / "metrics.jsonl"
    cfg = make_cfg(dataset, **{"train.metrics_path": str(mpath)})
    t = Trainer(cfg)
    poison_nan_batches(t, steps=[4])
    res = t.fit()
    assert res.steps == 12 and res.bad_steps == 1
    assert np.isfinite(res.last_loss)
    # every table stayed finite — the poisoned update never landed
    for name, tab in t.state.tables.items():
        assert np.isfinite(np.asarray(tab)).all(), name
    recs = [json.loads(l) for l in open(mpath)]
    skipped = [r for r in recs if r.get("nonfinite_skipped")]
    assert len(skipped) == 1 and skipped[0]["step"] == 4
    # the logger parent dir was created lazily and the handle closed in
    # fit's finally (satellite: MetricsLogger lifecycle)
    assert t.metrics._f is None


def test_nan_batch_guard_off_poisons_state(dataset):
    """Negative control: with the guard off a single NaN batch poisons
    the tables — the reference behavior the guard exists to prevent."""
    cfg = make_cfg(dataset, **{"train.nonfinite_guard": "off"})
    t = Trainer(cfg)
    poison_nan_batches(t, steps=[4])
    res = t.fit()
    assert not np.isfinite(res.last_loss)


def test_nan_batch_halt_commits_then_raises(dataset, tmp_path):
    ck = tmp_path / "ck"
    cfg = make_cfg(
        dataset,
        **{"train.nonfinite_guard": "halt", "train.checkpoint_dir": str(ck)},
    )
    t = Trainer(cfg)
    poison_nan_batches(t, steps=[4])
    with pytest.raises(NonFiniteHalt, match="non-finite guard aborted"):
        t.fit()
    steps = committed_steps(str(ck))
    assert steps, "halt must commit a checkpoint before raising"
    # the committed state is the last GOOD one: finite everywhere
    t2 = Trainer(make_cfg(dataset, **{"train.checkpoint_dir": str(ck)}))
    assert t2.maybe_restore()
    for name, tab in t2.state.tables.items():
        assert np.isfinite(np.asarray(tab)).all(), name


def test_halt_on_final_step_still_writes_staged_log_record(dataset, tmp_path):
    """The XF110 one-behind log staging must not lose the halting
    step's record: a NaN on the run's LAST data step halts post-loop,
    and the staged metrics line (the run's most diagnostic one) is
    written before NonFiniteHalt raises."""
    mpath = tmp_path / "m" / "metrics.jsonl"
    cfg = make_cfg(
        dataset,
        **{"train.nonfinite_guard": "halt",
           "train.metrics_path": str(mpath)},
    )
    t = Trainer(cfg)
    poison_nan_batches(t, steps=[12])  # 600 rows / 100 x 2 epochs = final
    with pytest.raises(NonFiniteHalt):
        t.fit()
    recs = [json.loads(l) for l in open(mpath)]
    steps = [r for r in recs if "loss" in r and "step" in r]
    assert [r["step"] for r in steps][-1] == 12
    assert steps[-1]["loss"] is None  # discarded step: strict-JSON null
    assert any(r.get("nonfinite_halt") for r in recs)


def test_consecutive_bad_steps_abort_under_skip(dataset, tmp_path):
    ck = tmp_path / "ck"
    cfg = make_cfg(
        dataset,
        **{
            "train.nonfinite_max_consecutive": 3,
            "train.checkpoint_dir": str(ck),
            "train.epochs": 4,
        },
    )
    t = Trainer(cfg)
    poison_nan_batches(t, steps=range(5, 100))  # everything from step 5 on
    with pytest.raises(NonFiniteHalt, match="3 consecutive"):
        t.fit()
    assert committed_steps(str(ck))


def test_bad_guard_mode_rejected(dataset):
    with pytest.raises(ValueError, match="nonfinite_guard"):
        Trainer(make_cfg(dataset, **{"train.nonfinite_guard": "maybe"}))


def test_nan_batch_skipped_on_mesh(dataset):
    """The guard through the sharded engines: FM routes to the fullshard
    sorted engine on a 4x2 mesh (parallel/sorted_fullshard.py), LR to the
    GSPMD row-major step (parallel/train_step.py); the flag is replicated
    and the discard rank-symmetric."""
    from xflow_tpu.parallel.mesh import make_mesh

    for model in ("fm", "lr"):
        cfg = make_cfg(
            dataset,
            **{
                "model.name": model,
                "mesh.data": 4,
                "mesh.table": 2,
                # 2^14 slots: the fullshard engine needs num_slots
                # divisible by data*table*WINDOW = 8*2048
                "data.log2_slots": 14,
                "train.epochs": 1,
            },
        )
        mesh = make_mesh(cfg)
        t = Trainer(cfg, mesh=mesh)
        if model == "fm":
            assert t._mesh_engine == "fullshard"
        poison_nan_batches(t, steps=[2])
        res = t.fit()
        assert res.bad_steps == 1, model
        assert np.isfinite(res.last_loss), model
        for name, tab in t.state.tables.items():
            assert np.isfinite(np.asarray(tab)).all(), (model, name)


# ------------------------------------------------- checkpoint self-healing
def _fit_with_checkpoints(dataset, tmp_path, **extra):
    ck = tmp_path / "ck"
    cfg = make_cfg(
        dataset,
        **{"train.checkpoint_dir": str(ck), "train.checkpoint_every": 5, **extra},
    )
    t = Trainer(cfg)
    t.fit()
    return cfg, ck, t


def test_restore_walks_back_from_truncated_npz(dataset, tmp_path):
    """Acceptance: restore recovers from the previous committed step when
    the newest state.npz is truncated — driven through the operator CLI
    (tools/corrupt_ckpt.py) so the tool and the tests share one injector."""
    cfg, ck, t1 = _fit_with_checkpoints(dataset, tmp_path)
    steps = committed_steps(str(ck))
    assert steps == [12, 10, 5]
    r = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "corrupt_ckpt.py"),
         "--dir", str(ck), "--mode", "truncate"],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout)["corrupted"].endswith("step_12/state.npz")
    t2 = Trainer(cfg)
    assert t2.maybe_restore()
    assert int(t2.state.step) == 10  # healed: newest skipped, previous loaded


def test_restore_walks_back_from_bitflipped_npz(dataset, tmp_path):
    cfg, ck, _ = _fit_with_checkpoints(dataset, tmp_path)
    corrupt_npz_checkpoint(str(ck), mode="bitflip", count=64, seed=3)
    t2 = Trainer(cfg)
    assert t2.maybe_restore()
    assert int(t2.state.step) in (5, 10)  # npz CRC catches the flip


def test_restore_all_corrupt_raises_with_reasons(dataset, tmp_path):
    cfg, ck, _ = _fit_with_checkpoints(dataset, tmp_path)
    for s in committed_steps(str(ck)):
        corrupt_npz_checkpoint(str(ck), step=s, mode="truncate", keep_frac=0.1)
    t2 = Trainer(cfg)
    with pytest.raises(RuntimeError, match="no loadable checkpoint"):
        t2.maybe_restore()


def test_orbax_restore_walks_back(dataset, tmp_path):
    pytest.importorskip("orbax.checkpoint")
    cfg, ck, _ = _fit_with_checkpoints(
        dataset, tmp_path, **{"train.checkpoint_format": "orbax"}
    )
    steps = orbax_steps(str(ck))
    assert steps[0] == 12 and len(steps) >= 2
    corrupt_orbax_checkpoint(str(ck), mode="truncate", keep_frac=0.05)
    t2 = Trainer(cfg)
    assert t2.maybe_restore()
    assert int(t2.state.step) < 12  # newest skipped


def test_save_cleans_stale_uncommitted_dir(dataset, tmp_path):
    """A crashed prior save leaves an uncommitted step_N dir; the next
    save of the same step must not mix generations of files in it."""
    ck = tmp_path / "ck"
    stale = ck / "step_12"
    os.makedirs(stale)
    with open(stale / "state.npz", "w") as f:
        f.write("debris from a crashed save")
    with open(stale / "leftover.tmp", "w") as f:
        f.write("junk")
    cfg = make_cfg(dataset, **{"train.checkpoint_dir": str(ck)})
    t = Trainer(cfg)
    t.fit()  # ends at step 12 — the same dir the stale debris occupies
    assert committed_steps(str(ck)) == [12]
    assert not (stale / "leftover.tmp").exists()
    t2 = Trainer(cfg)
    assert t2.maybe_restore() and int(t2.state.step) == 12


def test_keep_checkpoints_retention_and_sweep(dataset, tmp_path):
    ck = tmp_path / "ck"
    # plant stale uncommitted debris that the retention sweep must clear
    os.makedirs(ck / "step_3")
    with open(ck / "step_3" / "state.npz", "w") as f:
        f.write("partial")
    cfg = make_cfg(
        dataset,
        **{
            "train.checkpoint_dir": str(ck),
            "train.checkpoint_every": 5,
            "train.keep_checkpoints": 2,
        },
    )
    Trainer(cfg).fit()
    assert committed_steps(str(ck)) == [12, 10]  # step 5 pruned
    assert not (ck / "step_3").exists()  # stale dir swept
    assert not (ck / "step_5").exists()


# --------------------------------------------------- bad-record quarantine
def test_bad_rows_budget_raises(tmp_path):
    from xflow_tpu.data.pipeline import BadRecordError, batch_iterator

    shard = tmp_path / "junk-00000"
    info = write_malformed_libffm(str(shard), n_good=30, n_bad=6, seed=1)
    assert info["bad"] == 6
    cfg = make_cfg(tmp_path, **{"data.max_bad_rows": 3, "data.batch_size": 16}).data
    with pytest.raises(BadRecordError, match="max_bad_rows=3"):
        list(batch_iterator(str(shard), cfg))


def test_bad_rows_counted_and_quarantined(tmp_path):
    from xflow_tpu.data.pipeline import batch_iterator, count_batches

    shard = tmp_path / "junk-00000"
    info = write_malformed_libffm(
        str(shard), n_good=30, n_bad=6, seed=2, truncated_tail=True
    )
    qpath = tmp_path / "q" / "quarantine.jsonl"
    cfg = make_cfg(
        tmp_path,
        **{
            "data.max_bad_rows": 100,
            "data.quarantine_path": str(qpath),
            "data.batch_size": 16,
        },
    ).data
    batches = list(batch_iterator(str(shard), cfg))
    # bad rows are counted, NOT dropped: the batch count still matches
    # the row counters (the multi-process coordination contract)
    assert sum(int((np.asarray(b.row_mask) > 0).sum()) for b in batches) == info["rows"]
    assert len(batches) == count_batches(str(shard), cfg)
    recs = [json.loads(l) for l in open(qpath)]
    assert len(recs) == info["bad"]
    assert all(r["source"] == str(shard) for r in recs)


def test_trainer_survives_bad_rows_within_budget(tmp_path):
    """A shard with junk inside trains to completion when the budget
    allows — bad rows contribute a zero-feature example (logit 0), not a
    crash and not a poisoned table — and the quarantine file holds ONE
    record per bad row (first pass only), not one per epoch."""
    shard = tmp_path / "train-00000"
    info = write_malformed_libffm(str(shard), n_good=90, n_bad=5, seed=3)
    qpath = tmp_path / "quarantine.jsonl"
    cfg = make_cfg(
        tmp_path,
        **{
            "data.batch_size": 20,
            "data.max_bad_rows": 10,
            "data.quarantine_path": str(qpath),
            "train.epochs": 2,
            "data.log2_slots": 10,
            "model.num_fields": 6,
        },
    )
    res = Trainer(cfg).fit()
    assert res.steps > 0 and np.isfinite(res.last_loss)
    assert len(open(qpath).readlines()) == info["bad"]


def test_eval_never_enforces_bad_row_budget(tmp_path, monkeypatch):
    """The budget stops garbage from TRAINING in; a junk-heavy TEST
    shard must not kill the predict pass of a finished model."""
    monkeypatch.chdir(tmp_path)
    generate_shards(
        str(tmp_path / "train"), 1, 200, num_fields=5, ids_per_field=30, seed=0
    )
    write_malformed_libffm(
        str(tmp_path / "test-00000"), n_good=40, n_bad=8, seed=5
    )
    cfg = make_cfg(
        tmp_path,
        **{
            "data.test_path": str(tmp_path / "test"),
            "data.max_bad_rows": 3,  # below the test shard's 8 bad rows
            "train.epochs": 1,
        },
    )
    t = Trainer(cfg)
    t.fit()
    auc, ll = t.evaluate(dump=False)  # must complete, not BadRecordError
    assert np.isfinite(ll)


# ------------------------------------------------------ pipeline lifecycle
def test_prefetch_worker_exits_when_consumer_abandons():
    from xflow_tpu.data.pipeline import prefetch

    started = threading.Event()

    def slow_infinite():
        i = 0
        while True:
            started.set()
            yield i
            i += 1

    it = prefetch(iter(slow_infinite()), depth=2)
    assert next(it) == 0
    started.wait(timeout=10)
    it.close()  # the consumer walks away mid-epoch
    deadline = time.time() + 10
    while time.time() < deadline:
        if not any(
            t.name == "xflow-prefetch" and t.is_alive()
            for t in threading.enumerate()
        ):
            break
        time.sleep(0.05)
    alive = [t.name for t in threading.enumerate()
             if t.name == "xflow-prefetch" and t.is_alive()]
    assert not alive, "prefetch worker leaked after consumer close()"


def test_prefetch_propagates_producer_error():
    from xflow_tpu.data.pipeline import prefetch

    def boom():
        yield 1
        raise OSError("disk on fire")

    it = prefetch(iter(boom()))
    assert next(it) == 1
    with pytest.raises(OSError, match="disk on fire"):
        next(it)


def test_metrics_logger_reopens_after_close(tmp_path):
    from xflow_tpu.train.trainer import MetricsLogger

    path = tmp_path / "sub" / "dir" / "m.jsonl"
    ml = MetricsLogger(str(path))
    ml.log({"a": 1})
    ml.close()
    ml.log({"b": 2})  # reopens in append mode
    ml.close()
    recs = [json.loads(l) for l in open(path)]
    assert len(recs) == 2 and recs[0]["a"] == 1 and recs[1]["b"] == 2
    # both appends carry the provenance stamp (PR 2: joinable streams)
    assert all("ts" in r and "rank" in r and "run_id" in r for r in recs)


# ------------------------------------------------------------- killed rank
def _rank_pids(marker: bytes, rank: int):
    """Pids whose environment carries `marker` AND XFLOW_PROCESS_ID=rank."""
    want = f"XFLOW_PROCESS_ID={rank}".encode() + b"\0"
    out = []
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        try:
            with open(f"/proc/{pid}/environ", "rb") as f:
                env = f.read()
            if marker in env and want in env:
                out.append(int(pid))
        except OSError:
            continue
    return out


def test_killed_rank_committed_checkpoint_recovers(tmp_path):
    """SIGKILL one rank of a 2-'host' launch-dist run mid-training: the
    run dies, but the checkpoints committed before the kill survive (the
    commit-marker + atomic-write protocol) and restore into a fresh
    trainer — preemption-by-force-kill loses at most checkpoint_every
    steps, never the run (mirrors test_launch_dist.py's harness)."""
    from tests.test_launch_dist import _clean_env, _fake_ssh, _free_port
    from tests.test_launch_local import require_multiproc_cpu

    require_multiproc_cpu()
    generate_shards(str(tmp_path / "train"), 2, 4000, num_fields=4, ids_per_field=50)
    hosts = tmp_path / "hosts"
    hosts.write_text("127.0.0.1\n127.0.0.1\n")
    marker = f"XFLOW_FAULTKILL_{os.getpid()}"
    p = subprocess.Popen(
        [sys.executable, "-m", "xflow_tpu", "launch-dist",
         "--hosts", str(hosts), "--port", str(_free_port()),
         "--ssh-cmd", _fake_ssh(tmp_path),
         "--workdir", str(tmp_path / "rank{rank}"),
         "--python", sys.executable,
         "--env", "JAX_PLATFORMS=cpu",
         "--env", "PYTHONPATH=" + REPO_ROOT,
         "--env", marker + "=1",
         "--", "--train", str(tmp_path / "train"),
         "--batch-size", "20", "--model", "lr", "--epochs", "100000",
         "--log2-slots", "10", "--checkpoint-dir", "ckpt",
         "--set", "model.num_fields=4", "--set", "data.max_nnz=8",
         "--set", "train.pred_dump=false", "--set", "train.checkpoint_every=10"],
        env=_clean_env(), stdout=subprocess.DEVNULL,
        stderr=open(tmp_path / "launcher.err", "w"),
    )
    ck = tmp_path / "rank0" / "ckpt"
    try:
        deadline = time.time() + 300  # tight: typical commit lands in ~30 s
        committed = []
        while time.time() < deadline:
            committed = committed_steps(str(ck))
            if committed:
                break
            if p.poll() is not None:
                err = open(tmp_path / "launcher.err").read()
                if "Multiprocess computations aren't implemented" in err:
                    # this jax build cannot run multi-process CPU at all
                    # (every two-process test fails the same way); the
                    # killed-rank drill needs a capable runtime
                    pytest.skip("multi-process CPU unsupported by this jax build")
                assert False, f"launcher died before a checkpoint landed:\n{err[-2000:]}"
            time.sleep(0.3)
        assert committed, "no committed checkpoint within the deadline"
        victims = _rank_pids(marker.encode(), rank=1)
        assert victims, "rank 1 process not found"
        for pid in victims:
            os.kill(pid, signal.SIGKILL)  # the simulated hardware loss
        # no graceful teardown from here: kill the launcher too (its
        # die-with-connection watcher reaps the surviving rank)
        os.kill(p.pid, signal.SIGKILL)
        p.wait()
        # recovery: what was committed before the kill restores cleanly
        steps_after = committed_steps(str(ck))
        assert steps_after and steps_after[0] >= committed[0]
        cfg = override(Config(), **{
            "data.log2_slots": 10, "data.batch_size": 20, "data.max_nnz": 8,
            "model.num_fields": 4, "train.checkpoint_dir": str(ck),
        })
        t = Trainer(cfg)
        assert t.maybe_restore()
        assert int(t.state.step) == steps_after[0]
        for name, tab in t.state.tables.items():
            assert np.isfinite(np.asarray(tab)).all(), name
    finally:
        for pid in {p.pid, *_rank_pids(marker.encode(), 0), *_rank_pids(marker.encode(), 1)}:
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass
