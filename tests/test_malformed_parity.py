"""Malformed-input parity: the row counters and the parsers must agree.

Multi-process step coordination rests on one invariant: for ANY input —
junk labels, feature-less lines, separator-free lines, truncated final
lines — `count_rows` (Python predicate) and `native_count_rows` (C
predicate) report the same number, and the matching parser yields
exactly that many rows (so `count_batches` predicts the batch stream
exactly). The trainer's `_coordinated_batches` drift check fires at run
time on any mismatch; these tests pin the predicates directly,
property-style over seeded random junk compositions
(xflow_tpu.testing.faults.write_malformed_libffm) plus hand-picked edge
files.
"""

import numpy as np
import pytest

from xflow_tpu.config import Config, override
from xflow_tpu.data.libffm import count_rows, iter_examples
from xflow_tpu.data.pipeline import batch_iterator, count_batches
from xflow_tpu.testing.faults import write_malformed_libffm


def _data_cfg(**kw):
    base = {
        "data.batch_size": 8,
        "data.max_nnz": 8,
        "data.log2_slots": 10,
        "data.max_bad_rows": -1,
    }
    base.update(kw)
    return override(Config(), **base).data


def _native_available() -> bool:
    try:
        from xflow_tpu.data.native import get_lib

        get_lib()
        return True
    except Exception:
        return False


@pytest.mark.parametrize("seed", range(6))
@pytest.mark.parametrize("truncated_tail", [False, True])
def test_counters_match_parsers_on_junk(tmp_path, seed, truncated_tail):
    """Property over random junk compositions: both counters equal the
    planted row count, and both parser paths yield exactly the predicted
    batches with exactly that many real rows."""
    path = str(tmp_path / f"junk-{seed}")
    info = write_malformed_libffm(
        path, n_good=20 + seed * 3, n_bad=seed % 4, n_junk_label=seed % 3,
        n_nonrows=5, seed=seed, truncated_tail=truncated_tail,
    )
    rows = info["rows"]
    assert count_rows(path) == rows
    assert len(list(iter_examples(path, 10))) == rows

    cfg_py = _data_cfg(**{"data.use_native_parser": False})
    expected_batches = -(-rows // cfg_py.batch_size) if rows else 0
    assert count_batches(path, cfg_py) == expected_batches
    got_py = list(batch_iterator(path, cfg_py))
    assert len(got_py) == expected_batches
    assert sum(int((np.asarray(b.row_mask) > 0).sum()) for b in got_py) == rows

    if not _native_available():
        pytest.skip("native toolchain unavailable")
    from xflow_tpu.data.native import native_count_rows

    assert native_count_rows(path, cfg_py.block_bytes) == rows
    cfg_nat = _data_cfg(**{"data.use_native_parser": True})
    got_nat = list(batch_iterator(path, cfg_nat))
    assert len(got_nat) == expected_batches
    assert sum(int((np.asarray(b.row_mask) > 0).sum()) for b in got_nat) == rows
    # full batch parity, not just counts: identical labels/slots/masks
    for bp, bn in zip(got_py, got_nat):
        np.testing.assert_array_equal(bp.labels, bn.labels)
        np.testing.assert_array_equal(bp.slots, bn.slots)
        np.testing.assert_array_equal(bp.mask, bn.mask)
        np.testing.assert_array_equal(bp.row_mask, bn.row_mask)


EDGE_FILES = {
    # label-only lines, trailing whitespace flavors, separator subtleties
    "label_only": ("1\n0\n", 0),
    "label_trailing_ws": ("1   \n0\t\n", 0),  # strip first; no separator left
    "space_separator": ("1 0:5:1\n", 1),
    "sep_only_junk": ("abc def\n", 1),  # junk label + junk token = a bad row
    "crlf": ("1\t0:5:1\r\n0\t1:6:1\r\n", 2),
    "empty": ("", 0),
    "newlines_only": ("\n\n\n", 0),
    "truncated_no_newline": ("1\t0:5:1", 1),
    "truncated_mid_token": ("1\t0:5:1\n0\t3:77", 2),
    "unicode_ws": ("1 label\n", 0),  # NBSP is NOT a separator (C parity)
}


@pytest.mark.parametrize("name", sorted(EDGE_FILES))
def test_counter_parity_edge_files(tmp_path, name):
    text, rows = EDGE_FILES[name]
    path = str(tmp_path / name)
    with open(path, "w") as f:
        f.write(text)
    assert count_rows(path) == rows, name
    assert len(list(iter_examples(path, 10))) == rows, name
    if _native_available():
        from xflow_tpu.data.native import native_count_rows

        assert native_count_rows(path, 1 << 20) == rows, name
