import jax.numpy as jnp
import numpy as np

from tests.oracles import FTRLOracle
from xflow_tpu.config import Config, override
from xflow_tpu.optim import get_optimizer

CFG = override(Config(), **{"data.log2_slots": 6})
N = 64


def test_ftrl_matches_per_key_oracle_over_steps():
    opt = get_optimizer("ftrl")
    tables = {"w": jnp.zeros((N,), jnp.float32)}
    state = opt.init_state(tables)
    oracle = FTRLOracle()
    rng = np.random.default_rng(0)
    for step in range(20):
        g = np.zeros((N,), np.float32)
        touched = rng.choice(N, size=10, replace=False)
        g[touched] = rng.normal(size=10).astype(np.float32)
        tables, state = opt.apply(tables, state, {"w": jnp.asarray(g)}, CFG)
        for k in touched:
            oracle.push(int(k), float(g[k]))
    w = np.asarray(tables["w"], np.float64)
    for k in range(N):
        np.testing.assert_allclose(w[k], oracle.pull(k), rtol=1e-4, atol=1e-6)


def test_ftrl_zero_gradient_is_noop():
    opt = get_optimizer("ftrl")
    rng = np.random.default_rng(1)
    tables = {"w": jnp.asarray(rng.normal(size=(N,)).astype(np.float32))}
    state = opt.init_state(tables)
    # one real update to move n/z off zero, then a zero push
    g1 = jnp.asarray(rng.normal(size=(N,)).astype(np.float32))
    tables, state = opt.apply(tables, state, {"w": g1}, CFG)
    t2, s2 = opt.apply(tables, state, {"w": jnp.zeros((N,))}, CFG)
    np.testing.assert_allclose(np.asarray(t2["w"]), np.asarray(tables["w"]), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(s2["w"]["n"]), np.asarray(state["w"]["n"]))
    np.testing.assert_allclose(np.asarray(s2["w"]["z"]), np.asarray(state["w"]["z"]))


def test_ftrl_zero_push_on_fresh_random_table_is_noop():
    # Lazy-init parity (ADVICE r1, ftrl.h:113-120): a slot that has NEVER
    # received a gradient must keep its build-time random init — the
    # reference only constructs entries on first push, so untouched v-table
    # rows stay at their ~N(0,1)*1e-2 init. A dense z→w recompute would
    # zero them on step 1.
    opt = get_optimizer("ftrl")
    rng = np.random.default_rng(3)
    w0 = rng.normal(size=(N,)).astype(np.float32) * 1e-2
    tables = {"v": jnp.asarray(w0)}
    state = opt.init_state(tables)
    t2, s2 = opt.apply(tables, state, {"v": jnp.zeros((N,))}, CFG)
    np.testing.assert_array_equal(np.asarray(t2["v"]), w0)
    np.testing.assert_array_equal(np.asarray(s2["v"]["n"]), np.zeros((N,)))
    np.testing.assert_array_equal(np.asarray(s2["v"]["z"]), np.zeros((N,)))
    # and a partial push only touches the pushed slots
    g = np.zeros((N,), np.float32)
    g[:4] = 1.0
    t3, _ = opt.apply(tables, state, {"v": jnp.asarray(g)}, CFG)
    np.testing.assert_array_equal(np.asarray(t3["v"][4:]), w0[4:])
    assert not np.array_equal(np.asarray(t3["v"][:4]), w0[:4])


def test_ftrl_sparsity():
    # tiny gradients must leave w exactly 0 (soft threshold λ1)
    opt = get_optimizer("ftrl")
    tables = {"w": jnp.zeros((4,), jnp.float32)}
    state = opt.init_state(tables)
    tables, state = opt.apply(tables, state, {"w": jnp.full((4,), 1e-6)}, CFG)
    assert float(jnp.abs(tables["w"]).max()) == 0.0


def test_ftrl_vector_table():
    opt = get_optimizer("ftrl")
    tables = {"v": jnp.zeros((8, 3), jnp.float32)}
    state = opt.init_state(tables)
    oracle = FTRLOracle(dim=(3,))
    rng = np.random.default_rng(2)
    for _ in range(5):
        g = rng.normal(size=(8, 3)).astype(np.float32)
        tables, state = opt.apply(tables, state, {"v": jnp.asarray(g)}, CFG)
        for k in range(8):
            oracle.push(k, g[k])
    for k in range(8):
        np.testing.assert_allclose(
            np.asarray(tables["v"][k], np.float64), oracle.pull(k), rtol=1e-4, atol=1e-6
        )


def test_sgd_update():
    opt = get_optimizer("sgd")
    tables = {"w": jnp.ones((4,), jnp.float32)}
    state = opt.init_state(tables)
    g = jnp.asarray([1.0, -1.0, 0.0, 2.0])
    tables, state = opt.apply(tables, state, {"w": g}, CFG)
    np.testing.assert_allclose(
        np.asarray(tables["w"]), [1 - 1e-3, 1 + 1e-3, 1.0, 1 - 2e-3], rtol=1e-6
    )
