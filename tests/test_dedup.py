"""Host-side batch dedup for the row-major paths (data.dedup,
ops/sorted_table.dedup_slots — the reference's per-minibatch unique-key
Pull, lr_worker.cc:150-165, as a two-level device gather)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from xflow_tpu.config import Config, override
from xflow_tpu.models import get_model
from xflow_tpu.ops.sorted_table import dedup_slots
from xflow_tpu.optim import get_optimizer
from xflow_tpu.train.state import init_state
from xflow_tpu.train.step import make_train_step

LOG2 = 12
S = 1 << LOG2
B, F = 64, 8


def _zipf_batch(rng, hot=32):
    """Heavily skewed slots: most occurrences hit `hot` ids."""
    slots = np.where(
        rng.random((B, F)) < 0.9,
        rng.integers(0, hot, (B, F)),
        rng.integers(0, S, (B, F)),
    ).astype(np.int32)
    return {
        "slots": slots,
        "fields": np.broadcast_to(np.arange(F, dtype=np.int32), (B, F)).copy(),
        "mask": (rng.random((B, F)) < 0.9).astype(np.float32),
        "labels": (rng.random(B) < 0.4).astype(np.float32),
        "row_mask": np.ones((B,), np.float32),
    }


def test_dedup_slots_roundtrip_and_overflow():
    rng = np.random.default_rng(0)
    slots = rng.integers(0, 50, (B, F)).astype(np.int32)
    got = dedup_slots(slots, cap=64)
    assert got is not None
    u, inv = got
    assert u.shape == (64,)
    np.testing.assert_array_equal(u[inv], slots)  # exact reconstruction
    assert dedup_slots(slots, cap=16) is None  # overflow -> caller falls back


@pytest.mark.parametrize("model_name", ["lr", "fm", "mvm"])
def test_dedup_training_equality(model_name):
    """A few FTRL steps with and without the deduped gather end at
    identical tables (the two-level gather is the same math)."""
    cfg = override(
        Config(),
        **{
            "model.name": model_name,
            "model.num_fields": F,
            "model.v_dim": 3,
            "data.log2_slots": LOG2,
            "data.batch_size": B,
            "data.max_nnz": F,
            "data.sorted_layout": "off",  # force the row-major path
        },
    )
    model, opt = get_model(model_name), get_optimizer("ftrl")
    rng = np.random.default_rng(1)
    batches = [_zipf_batch(rng) for _ in range(3)]
    step = make_train_step(model, opt, cfg)

    states = {}
    for dedup in (False, True):
        st = init_state(model, opt, cfg)
        for b in batches:
            arrays = {k: jnp.asarray(v) for k, v in b.items()}
            if dedup:
                u, inv = dedup_slots(b["slots"], cap=B * F // 2)
                arrays["unique_slots"] = jnp.asarray(u)
                arrays["inverse"] = jnp.asarray(inv)
            st, _ = step(st, arrays)
        states[dedup] = st
    for n in states[False].tables:
        np.testing.assert_allclose(
            np.asarray(states[True].tables[n]),
            np.asarray(states[False].tables[n]),
            rtol=1e-6, atol=1e-7,
            err_msg=f"{model_name} table {n} diverged under dedup",
        )


def test_trainer_first_batch_decides(tmp_path):
    from xflow_tpu.data.schema import SparseBatch
    from xflow_tpu.train.trainer import Trainer

    cfg = override(
        Config(),
        **{
            "model.name": "lr",
            "model.num_fields": F,
            "data.log2_slots": LOG2,
            "data.batch_size": B,
            "data.max_nnz": F,
        },
    )
    rng = np.random.default_rng(2)

    def sb(slots):
        return SparseBatch(
            slots=slots,
            fields=np.zeros((B, F), np.int32),
            mask=np.ones((B, F), np.float32),
            labels=np.zeros((B,), np.float32),
            row_mask=np.ones((B,), np.float32),
        )

    # dedup default is OFF (measured single-chip loss; docs/PERF.md)
    assert Trainer(cfg)._dedup_cap == 0
    cfg = override(cfg, **{"data.dedup": "auto"})
    # skewed first batch -> dedup on and attached
    tr = Trainer(cfg)
    assert tr._dedup_cap > 0
    arrays = tr._batch_arrays(sb(np.zeros((B, F), np.int32)))
    assert "unique_slots" in arrays and tr._dedup_on is True
    # near-uniform FIRST batch -> decided off for the run: later batches
    # skip the host sort entirely (even skewed ones)
    tr2 = Trainer(cfg)
    distinct = np.arange(B * F, dtype=np.int32).reshape(B, F)
    arrays = tr2._batch_arrays(sb(distinct))
    assert "unique_slots" not in arrays and tr2._dedup_on is False
    arrays = tr2._batch_arrays(sb(np.zeros((B, F), np.int32)))
    assert "unique_slots" not in arrays
    # explicit off disables entirely
    tr3 = Trainer(override(cfg, **{"data.dedup": "off"}))
    assert tr3._dedup_cap == 0
