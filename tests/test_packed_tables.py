"""Packed [S/8, 8K] table storage (ops/sorted_table.pack_table).

TPU HBM buffers are (8, 128)-tiled: a logical [S, 11] f32 table is
stored [S, 128] — 11.6× its logical bytes (3 × 8 GB of FM FTRL state at
2^24 slots; the round-3 scale run OOM'd exactly there) — and every
elementwise optimizer pass runs at 11/128 lane efficiency. Packed
storage fixes both; consumers detect the layout FROM THE SHAPE
(`pack_of`), so these tests pin: layout equivalence of every op,
training equality against the logical layout, and checkpoint
cross-layout migration.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from xflow_tpu.config import Config, override
from xflow_tpu.models import get_model
from xflow_tpu.ops.sorted_table import (
    _gather_xla,
    _scatter_xla,
    pack_of,
    pack_table,
    plan_sorted_batch,
    table_gather_sorted,
    table_rows,
    unpack_table,
)
from xflow_tpu.optim import get_optimizer
from xflow_tpu.train.state import init_state
from xflow_tpu.train.step import make_train_step

LOG2 = 13
S = 1 << LOG2
K = 11
B, F = 64, 8


def test_pack_roundtrip_and_detection():
    t = np.arange(S * K, dtype=np.float32).reshape(S, K)
    tp = pack_table(t)
    assert tp.shape == (S // 8, 8 * K)
    np.testing.assert_array_equal(unpack_table(tp, K), t)
    # slot s lives at [s//8, (s%8)*K:(s%8+1)*K]
    np.testing.assert_array_equal(tp[3, 2 * K : 3 * K], t[3 * 8 + 2])
    assert pack_of(t, K) == 1
    assert pack_of(tp, K) == 8
    with pytest.raises(ValueError, match="neither"):
        pack_of(np.zeros((S, K + 1), np.float32), K)


def test_gather_scatter_layout_equivalence():
    """The windowed gather and its scatter VJP produce IDENTICAL results
    from logical and packed storage (packed gradient = packed logical
    gradient)."""
    rng = np.random.default_rng(0)
    t = rng.standard_normal((S, K)).astype(np.float32)
    slots = rng.integers(0, S, (B, F)).astype(np.int32)
    mask = np.ones((B, F), np.float32)
    plan = plan_sorted_batch(slots, mask, S)
    ss, wo = jnp.asarray(plan.sorted_slots), jnp.asarray(plan.win_off)

    got_l = table_gather_sorted(jnp.asarray(t), ss, wo, False, 1)
    got_p = table_gather_sorted(jnp.asarray(pack_table(t)), ss, wo, False, 8)
    np.testing.assert_array_equal(np.asarray(got_l), np.asarray(got_p))

    d = rng.standard_normal(got_l.shape).astype(np.float32)

    def grad_for(tbl, pack):
        _, vjp = jax.vjp(lambda x: table_gather_sorted(x, ss, wo, False, pack), tbl)
        return np.asarray(vjp(jnp.asarray(d))[0])

    g_l = grad_for(jnp.asarray(t), 1)
    g_p = grad_for(jnp.asarray(pack_table(t)), 8)
    assert g_p.shape == (S // 8, 8 * K)
    np.testing.assert_allclose(unpack_table(g_p, K), g_l, rtol=1e-6, atol=1e-7)


def test_xla_fallback_layout_equivalence():
    rng = np.random.default_rng(1)
    t = rng.standard_normal((S, K)).astype(np.float32)
    slots = jnp.asarray(rng.integers(0, S, 500).astype(np.int32))
    got_l = _gather_xla(jnp.asarray(t), slots, None, 1)
    got_p = _gather_xla(jnp.asarray(pack_table(t)), slots, None, 8)
    np.testing.assert_array_equal(np.asarray(got_l), np.asarray(got_p))
    d = rng.standard_normal(got_l.shape).astype(np.float32)
    s_l = _scatter_xla(jnp.asarray(d), slots, None, S, K, 1)
    s_p = _scatter_xla(jnp.asarray(d), slots, None, S, K, 8)
    np.testing.assert_allclose(
        unpack_table(np.asarray(s_p), K), np.asarray(s_l), rtol=1e-6, atol=1e-7
    )


def test_table_rows_layout_blind():
    rng = np.random.default_rng(2)
    t = rng.standard_normal((S, K)).astype(np.float32)
    slots = jnp.asarray(rng.integers(0, S, (B, F)).astype(np.int32))
    np.testing.assert_array_equal(
        np.asarray(table_rows(jnp.asarray(t), slots, K)),
        np.asarray(table_rows(jnp.asarray(pack_table(t)), slots, K)),
    )


@pytest.mark.parametrize("model_name", ["fm", "mvm"])
def test_training_equality_packed_vs_logical(model_name):
    """Full FTRL training through the sorted path ends at the same
    logical tables from either storage layout (states initialized from
    the SAME logical values; init RNG streams differ between layouts)."""
    from xflow_tpu.train.state import TrainState

    k = 3
    over = {
        "model.name": model_name,
        "model.num_fields": F,
        "model.v_dim": k,
        "data.log2_slots": LOG2,
        "data.batch_size": B,
        "data.max_nnz": F,
    }
    cfg_p = override(Config(), **over)
    cfg_l = override(Config(), **{**over, "data.packed_tables": "off"})
    model, opt = get_model(model_name), get_optimizer("ftrl")
    state_l = init_state(model, opt, cfg_l)
    # pack the SAME logical values into the packed state
    state_p = TrainState(
        tables={n: jnp.asarray(pack_table(np.asarray(t)))
                for n, t in state_l.tables.items()},
        opt_state={
            n: {kk: jnp.asarray(pack_table(np.asarray(v)))
                for kk, v in st.items()}
            for n, st in state_l.opt_state.items()
        },
        step=jnp.array(state_l.step),  # own copy: both steps donate their state
    )
    rng = np.random.default_rng(3)
    step_p = make_train_step(model, opt, cfg_p)
    step_l = make_train_step(model, opt, cfg_l)
    for _ in range(3):
        slots = rng.integers(0, S, (B, F)).astype(np.int32)
        fields = np.broadcast_to(np.arange(F, dtype=np.int32), (B, F)).copy()
        mask = (rng.random((B, F)) < 0.9).astype(np.float32)
        plan = plan_sorted_batch(slots, mask, S)
        batch = {
            "sorted_slots": jnp.asarray(plan.sorted_slots),
            "sorted_row": jnp.asarray(plan.sorted_row),
            "sorted_mask": jnp.asarray(plan.sorted_mask),
            "win_off": jnp.asarray(plan.win_off),
            "labels": jnp.asarray((rng.random(B) < 0.4).astype(np.float32)),
            "row_mask": jnp.ones((B,), jnp.float32),
        }
        if model_name == "mvm":
            pass  # product path: no sorted_fields needed
        state_p, m_p = step_p(state_p, batch)
        state_l, m_l = step_l(state_l, batch)
        assert float(m_p["loss"]) == pytest.approx(float(m_l["loss"]), rel=1e-6)
    for n in state_l.tables:
        K_n = state_l.tables[n].shape[-1]
        np.testing.assert_allclose(
            unpack_table(np.asarray(state_p.tables[n]), K_n),
            np.asarray(state_l.tables[n]),
            rtol=1e-6, atol=1e-7,
        )


def test_checkpoint_cross_layout_migration(tmp_path):
    """npz checkpoints store the LOGICAL layout; a packed run restores a
    logical checkpoint (and vice versa) via the reshape shim."""
    from xflow_tpu.train import checkpoint as ckpt

    over = {
        "model.name": "fm",
        "model.v_dim": 3,
        "data.log2_slots": LOG2,
    }
    cfg_p = override(Config(), **over)
    cfg_l = override(Config(), **{**over, "data.packed_tables": "off"})
    model, opt = get_model("fm"), get_optimizer("ftrl")
    state_p = init_state(model, opt, cfg_p)
    widths = {"wv": 4}
    path = ckpt.save(str(tmp_path / "c"), state_p, widths)
    stored = np.load(path + "/state.npz")
    assert stored["tables/wv"].shape == (S, 4)  # logical on disk
    # restore into a LOGICAL-layout run
    state_l = ckpt.restore(str(tmp_path / "c"), init_state(model, opt, cfg_l))
    np.testing.assert_array_equal(
        np.asarray(state_l.tables["wv"]),
        unpack_table(np.asarray(state_p.tables["wv"]), 4),
    )
    # and back into a PACKED-layout run
    state_p2 = ckpt.restore(str(tmp_path / "c"), init_state(model, opt, cfg_p))
    np.testing.assert_array_equal(
        np.asarray(state_p2.tables["wv"]), np.asarray(state_p.tables["wv"])
    )
