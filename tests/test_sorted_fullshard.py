"""Fully-sharded sorted engine (parallel/sorted_fullshard.py): equality
vs the single-device step across mesh shapes for FM and MVM, the
no-replication memory contract, buffer-capacity overflow, and trainer
integration (auto engine selection, multi-step training equality)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from xflow_tpu.config import Config, override
from xflow_tpu.models import get_model
from xflow_tpu.optim import get_optimizer
from xflow_tpu.ops.sorted_table import WINDOW
from xflow_tpu.parallel.mesh import make_mesh
from xflow_tpu.parallel.sorted_fullshard import (
    fullshard_batch_sharding,
    fullshard_capacity,
    make_fullshard_train_step,
    plan_fullshard_batch,
    validate_sorted_fullshard,
)
from xflow_tpu.parallel.train_step import shard_state
from xflow_tpu.train.state import init_state
from xflow_tpu.train.step import make_train_step

B, F = 64, 10
LOG2_SLOTS = 14  # 16384 = 8 * WINDOW: divisible for every 8-device mesh
S = 1 << LOG2_SLOTS


def cfg_for(model_name, d, t, **extra):
    over = {
        "model.name": model_name,
        "model.num_fields": 5,
        "data.log2_slots": LOG2_SLOTS,
        "data.batch_size": B,
        "data.max_nnz": F,
        "mesh.data": d,
        "mesh.table": t,
        **extra,
    }
    return override(Config(), **over)


def rand_batch(rng, nf=5):
    return {
        "slots": rng.integers(0, S, (B, F)).astype(np.int32),
        "fields": rng.integers(0, nf, (B, F)).astype(np.int32),
        "mask": (rng.random((B, F)) < 0.8).astype(np.float32),
        "labels": (rng.random(B) < 0.4).astype(np.float32),
        "row_mask": np.ones((B,), np.float32),
    }


def _place_fullshard(batch, cfg, mesh, with_fields):
    arrays = plan_fullshard_batch(
        batch["slots"], batch["mask"], cfg, mesh,
        fields=batch["fields"] if with_fields else None,
    )
    arrays["labels"] = batch["labels"]
    arrays["row_mask"] = batch["row_mask"]
    bsh = fullshard_batch_sharding(mesh, with_fields=with_fields)
    return {k: jax.device_put(jnp.asarray(v), bsh[k]) for k, v in arrays.items()}


@pytest.mark.parametrize("model_name", ["fm", "mvm", "ffm", "mvm_product"])
@pytest.mark.parametrize("mesh_shape", [(8, 1), (4, 2), (2, 4), (1, 8)])
def test_fullshard_step_matches_single_device(model_name, mesh_shape):
    d, t = mesh_shape
    # "mvm" plans WITH fields (the general segment mode); "mvm_product"
    # plans without them on exclusive-fields batches — the product-mode
    # custom VJP whose missing 'table'-axis cotangent restore diverged
    # at every T>1 (round-4 ADVICE; make_row_products restore_dP)
    product = model_name == "mvm_product"
    model_name = "mvm" if product else model_name
    # ffm: k=3 keeps the fused row width (1 + nf*k = 16) CI-sized
    extra = {"model.v_dim": 3} if model_name == "ffm" else {}
    cfg = cfg_for(model_name, d, t, **extra)
    model, opt = get_model(model_name), get_optimizer("ftrl")
    rng = np.random.default_rng(0)
    batches = [rand_batch(rng) for _ in range(3)]
    if product:
        for b in batches:
            # one occurrence per field: F=10 columns over nf=5 fields
            # would duplicate, so keep 5 columns live per row
            b["fields"] = np.broadcast_to(
                np.arange(F, dtype=np.int32) % 5, (B, F)
            ).copy()
            b["mask"] = b["mask"] * (np.arange(F) < 5)

    # single-device row-major reference
    state1 = init_state(model, opt, cfg)
    step1 = make_train_step(model, opt, cfg)
    losses1 = []
    for b in batches:
        state1, m = step1(state1, {k: jnp.asarray(v) for k, v in b.items()})
        losses1.append(float(m["loss"]))

    mesh = make_mesh(cfg, devices=jax.devices()[: d * t])
    state2 = shard_state(init_state(model, opt, cfg), mesh)
    step2 = make_fullshard_train_step(opt, cfg, mesh)
    losses2 = []
    for b in batches:
        state2, m = step2(
            state2,
            _place_fullshard(
                b, cfg, mesh, not product and model_name in ("mvm", "ffm")
            ),
        )
        losses2.append(float(m["loss"]))

    np.testing.assert_allclose(losses1, losses2, rtol=2e-5)
    for name in state1.tables:
        np.testing.assert_allclose(
            np.asarray(state1.tables[name]),
            np.asarray(state2.tables[name]),
            rtol=2e-4,
            atol=1e-6,
            err_msg=f"{model_name} table {name} diverged on mesh {mesh_shape}",
        )


def test_fullshard_no_replication():
    """The memory contract: every device holds EXACTLY S/(D*T) slots of
    each table and optimizer-state array — no data-axis replication
    (round-2 verdict missing #2)."""
    cfg = cfg_for("fm", 4, 2)
    mesh = make_mesh(cfg)
    model, opt = get_model("fm"), get_optimizer("ftrl")
    state = shard_state(init_state(model, opt, cfg), mesh)
    K = 1 + cfg.model.v_dim
    arrays = [state.tables["wv"], state.opt_state["wv"]["n"], state.opt_state["wv"]["z"]]
    for arr in arrays:
        shapes = {s.data.shape for s in arr.addressable_shards}
        # packed storage: each of the 8 devices owns S/8 slots = S/8/8
        # stored rows of 8*K (ops/sorted_table.pack_table)
        assert shapes == {(S // 8 // 8, 8 * K)}, shapes
        # 8 distinct shards — the whole array exists exactly once
        assert len(arr.addressable_shards) == 8
        starts = sorted(s.index[0].start or 0 for s in arr.addressable_shards)
        assert starts == [i * (S // 8 // 8) for i in range(8)]


def test_fullshard_capacity_overflow_raises():
    """More occurrences in one owner block than the buffer holds must
    fail loudly with the slack advice, not silently drop occurrences."""
    from xflow_tpu.ops.sorted_table import plan_sorted_batch
    from xflow_tpu.parallel.sorted_fullshard import fullshard_buffers

    slots = np.full((128, 10), 7, np.int32)  # 1280 occurrences, one block
    mask = np.ones((128, 10), np.float32)
    plan = plan_sorted_batch(slots, mask, S)
    with pytest.raises(ValueError, match="fullshard_slack"):
        fullshard_buffers(
            plan, D=4, T=2, cap=512, s_local=S // 8, slack=2.0, n_real=1280
        )


def test_fullshard_higher_slack_absorbs_skew():
    cfg = cfg_for("fm", 4, 2, **{"data.fullshard_slack": 16.0})
    mesh = make_mesh(cfg)
    rng = np.random.default_rng(3)
    b = rand_batch(rng)
    b["slots"][:] = 7
    arrays = plan_fullshard_batch(b["slots"], b["mask"], cfg, mesh)
    # all real occurrences are in (source-shard, block-0) buffers
    total = sum(
        float(arrays["fs_mask"][i].sum()) for i in range(arrays["fs_mask"].shape[0])
    )
    assert total == float(b["mask"].sum())


def test_fullshard_validation_messages():
    mesh = make_mesh(cfg_for("fm", 4, 2))
    with pytest.raises(ValueError, match="divisible by data\\*table\\*WINDOW"):
        validate_sorted_fullshard(cfg_for("fm", 4, 2, **{"data.log2_slots": 12}), mesh)
    with pytest.raises(ValueError, match="fused FM, MVM, and FFM"):
        validate_sorted_fullshard(cfg_for("lr", 4, 2), mesh)
    with pytest.raises(ValueError, match="fm_fused"):
        validate_sorted_fullshard(
            cfg_for("fm", 4, 2, **{"model.fm_fused": False}), mesh
        )
    cap = fullshard_capacity(cfg_for("fm", 4, 2), mesh)
    assert cap % 512 == 0 and cap >= 512


@pytest.mark.parametrize("model_name", ["fm", "mvm", "ffm"])
def test_trainer_fullshard_auto(model_name, tmp_path):
    """Trainer on a mesh auto-selects the fullshard engine for
    FM/MVM/FFM and trains to the same result as the single-device
    trainer."""
    from xflow_tpu.data.synth import generate_shards
    from xflow_tpu.train.trainer import Trainer

    generate_shards(str(tmp_path / "train"), 1, 128, num_fields=5,
                    ids_per_field=60, seed=0)
    over = {
        "data.train_path": str(tmp_path / "train"),
        "data.test_path": str(tmp_path / "train"),
        "train.epochs": 2,
        "train.pred_dump": False,
        "train.eval_buckets": 0,
    }
    if model_name == "ffm":
        over["model.v_dim"] = 3
    cfg = cfg_for(model_name, 4, 2, **over)
    mesh = make_mesh(cfg)
    t_mesh = Trainer(cfg, mesh=mesh)
    assert t_mesh._mesh_engine == "fullshard"
    res_mesh = t_mesh.fit()
    auc_mesh, ll_mesh = t_mesh.evaluate(dump=False)

    t_one = Trainer(cfg_for(model_name, 4, 2, **over, **{"data.sorted_layout": "off"}))
    res_one = t_one.fit()
    auc_one, ll_one = t_one.evaluate(dump=False)

    assert res_mesh.steps == res_one.steps
    np.testing.assert_allclose(res_mesh.last_loss, res_one.last_loss, rtol=2e-5)
    tname = "v" if model_name == "mvm" else "wv"
    np.testing.assert_allclose(
        np.asarray(t_mesh.state.tables[tname]),
        np.asarray(t_one.state.tables[tname]),
        rtol=2e-4, atol=1e-6,
    )
    assert abs(auc_mesh - auc_one) < 1e-6
    np.testing.assert_allclose(ll_mesh, ll_one, rtol=1e-5)


def test_trainer_auto_falls_back_to_gspmd_when_invalid(tmp_path):
    """log2_slots too small for the owner grid: auto keeps the GSPMD
    row-major path instead of failing."""
    from xflow_tpu.train.trainer import Trainer

    cfg = cfg_for("fm", 4, 2, **{"data.log2_slots": 12})
    mesh = make_mesh(cfg)
    t = Trainer(cfg, mesh=mesh)
    assert t._mesh_engine is None
    assert not t._sorted


def test_trainer_fullshard_overflow_falls_back_single_process(tmp_path):
    """A batch too skewed for the buffer capacity must NOT abort a
    single-process run: the trainer falls back to the GSPMD row-major
    step for that batch (state sharding is identical) and warns once."""
    from xflow_tpu.data.libffm import shard_path
    from xflow_tpu.train.trainer import Trainer

    # every row carries the SAME feature 4 of 8 times: half of all
    # occurrences land in one owner block, 4x the uniform expectation —
    # beyond slack 1.0, so the hot block's buffer overflows
    path = tmp_path / "train-00000"
    rng = np.random.default_rng(0)
    hot = " ".join(["0:0:1.0"] * 4)
    with open(path, "w") as f:
        for i in range(2048):
            feats = " ".join(
                f"{fg}:{rng.integers(0, 50)}:1.0" for fg in range(1, 5)
            )
            f.write(f"{i % 2}\t{hot} {feats}\n")
    cfg = cfg_for(
        "fm", 4, 2,
        **{
            "data.train_path": str(tmp_path / "train"),
            "data.batch_size": 2048,
            "data.max_nnz": 8,
            "train.epochs": 1,
            "train.pred_dump": False,
            "data.fullshard_slack": 1.0,
        },
    )
    mesh = make_mesh(cfg)
    t = Trainer(cfg, mesh=mesh)
    assert t._mesh_engine == "fullshard"
    res = t.fit()
    assert res.steps == 1
    assert t._fullshard_overflow_warned
    assert np.isfinite(res.last_loss)
