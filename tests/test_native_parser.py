"""C++ parser parity with the Python reference path, plus throughput sanity."""

import shutil
import time

import numpy as np
import pytest

from xflow_tpu.config import DataConfig
from xflow_tpu.data.libffm import iter_examples
from xflow_tpu.data.pipeline import examples_to_batches
from xflow_tpu.data.synth import generate_shards
from xflow_tpu.hashing import fnv1a64, slot_of

pytestmark = pytest.mark.skipif(shutil.which("g++") is None, reason="no g++")


def _native():
    from xflow_tpu.data import native

    return native


def test_hash_parity_with_python():
    native = _native()
    for tok in [b"", b"0", b"1163", b"a" * 100, "héllo".encode()]:
        for salt in (0, 1, 12345):
            assert native.native_hash(tok, salt) == fnv1a64(tok, salt)


def test_slot_parity_with_python():
    native = _native()
    rng = np.random.default_rng(0)
    for key in rng.integers(0, 1 << 63, 200, dtype=np.uint64):
        for log2 in (10, 22, 30):
            assert native.native_slot(int(key), log2) == slot_of(int(key), log2)


def _batches_python(path, cfg, bs):
    return list(
        examples_to_batches(
            iter_examples(path, cfg.log2_slots, cfg.hash_salt), bs, cfg.max_nnz, cfg.drop_remainder
        )
    )


def _batches_native(path, cfg, bs):
    native = _native()
    return list(native.native_batch_iterator(path, cfg, bs))


@pytest.mark.parametrize("bs", [32, 57])
def test_batch_parity_on_synth(tmp_path, bs):
    path = generate_shards(str(tmp_path / "s"), 1, 333, num_fields=7, ids_per_field=100, seed=4)[0]
    cfg = DataConfig(log2_slots=18, max_nnz=16)
    py = _batches_python(path, cfg, bs)
    nat = _batches_native(path, cfg, bs)
    assert len(py) == len(nat)
    for a, b in zip(py, nat):
        np.testing.assert_array_equal(a.slots, b.slots)
        np.testing.assert_array_equal(a.fields, b.fields)
        np.testing.assert_array_equal(a.mask, b.mask)
        np.testing.assert_array_equal(a.labels, b.labels)
        np.testing.assert_array_equal(a.row_mask, b.row_mask)


def test_batch_parity_on_golden():
    import os

    if not os.path.isdir("/root/reference/data"):
        pytest.skip("reference data not mounted")
    path = "/root/reference/data/small_train-00000"
    cfg = DataConfig(log2_slots=16, max_nnz=40)
    py = _batches_python(path, cfg, 64)
    nat = _batches_native(path, cfg, 64)
    assert len(py) == len(nat)
    for a, b in zip(py, nat):
        np.testing.assert_array_equal(a.slots, b.slots)
        np.testing.assert_array_equal(a.labels, b.labels)


def test_truncation_counted(tmp_path):
    native = _native()
    p = tmp_path / "t.ffm"
    p.write_text("1\t0:1:1 1:2:1 2:3:1 3:4:1\n")
    cfg = DataConfig(log2_slots=10, max_nnz=2)
    stream = native._NativeBatchStream(str(p), cfg, 4)
    batches = list(stream)
    assert batches[0].mask[0].sum() == 2
    assert batches[0].fields[0, 0] == 0 and batches[0].fields[0, 1] == 1
    assert stream.truncated == 2  # counter surfaced after close


def test_stream_is_single_use(tmp_path):
    native = _native()
    p = tmp_path / "t.ffm"
    p.write_text("1\t0:1:1\n")
    stream = native._NativeBatchStream(str(p), DataConfig(log2_slots=10, max_nnz=4), 4)
    list(stream)
    with pytest.raises(RuntimeError):
        iter(stream)


def test_edge_case_parity_with_python(tmp_path):
    # zero-feature rows kept, CRLF endings, tab-separated feature tokens,
    # junk labels (atof semantics) — both parsers must agree
    p = tmp_path / "edge-00000"
    p.write_bytes(
        b"1\tfoo\n"              # labeled row, no valid features
        b"0\t0:5:1\r\n"          # CRLF
        b"1\t0:7:1\t1:8:1\n"     # tab-separated tokens
        b"junk\t0:9:1\n"          # junk label -> 0 (atof)
        b"0.5\t1:3:1"             # no trailing newline, fractional label
    )
    cfg = DataConfig(log2_slots=12, max_nnz=4)
    py = _batches_python(str(p), cfg, 8)
    nat = _batches_native(str(p), cfg, 8)
    assert len(py) == len(nat) == 1
    for a, b in zip(py, nat):
        np.testing.assert_array_equal(a.slots, b.slots)
        np.testing.assert_array_equal(a.fields, b.fields)
        np.testing.assert_array_equal(a.mask, b.mask)
        np.testing.assert_array_equal(a.labels, b.labels)
        np.testing.assert_array_equal(a.row_mask, b.row_mask)
    assert py[0].labels[0] == 1.0 and py[0].mask[0].sum() == 0  # kept, empty
    assert py[0].mask[2].sum() == 2  # both tab-separated tokens parsed
    assert py[0].labels[3] == 0.0  # junk label
    assert py[0].labels[4] == 1.0  # 0.5 > 1e-7


def test_junk_fgid_parity_with_python(tmp_path):
    # a non-numeric / partially-numeric field id must parse identically in
    # both paths (strtod semantics: longest numeric prefix, 0 for junk) —
    # round-1 divergence: the Python path crashed on these
    p = tmp_path / "junk-00000"
    p.write_text(
        "1\tabc:77:1\n"       # junk fgid -> 0
        "0\t3x:12:1\n"        # numeric prefix -> 3
        "1\t2.9:13:1\n"       # fractional -> int(2.9) = 2
        "0\t-1e1:14:1 :15:1\n"  # scientific -> -10; empty fgid -> 0
        "1\tinf:16:1 nan:17:1\n"   # strtod parses these; i32: saturate / 0
        "0\t1e300:18:1 -inf:19:1\n"  # overflow saturation both signs
        "1\t0x10:20:1 1_0:21:1\n"  # C99 hex float -> 16; '_' stops strtod -> 1
    )
    cfg = DataConfig(log2_slots=12, max_nnz=4)
    py = _batches_python(str(p), cfg, 8)
    nat = _batches_native(str(p), cfg, 8)
    assert len(py) == len(nat) == 1
    for a, b in zip(py, nat):
        np.testing.assert_array_equal(a.fields, b.fields)
        np.testing.assert_array_equal(a.slots, b.slots)
        np.testing.assert_array_equal(a.mask, b.mask)
    assert py[0].fields[0, 0] == 0
    assert py[0].fields[1, 0] == 3
    assert py[0].fields[2, 0] == 2
    assert py[0].fields[3, 0] == -10 and py[0].fields[3, 1] == 0
    assert py[0].fields[4, 0] == 2**31 - 1 and py[0].fields[4, 1] == 0
    assert py[0].fields[5, 0] == 2**31 - 1 and py[0].fields[5, 1] == -(2**31)
    assert py[0].fields[6, 0] == 16 and py[0].fields[6, 1] == 1


def test_whitespace_and_label_sep_parity(tmp_path):
    # round-2 review findings: label-only lines with trailing whitespace
    # must NOT be rows in either parser; the label separator is the first
    # TAB if any, else the first space; inf/nan-with-junk labels parse via
    # strtod-prefix semantics in both
    from xflow_tpu.data.libffm import count_rows
    from xflow_tpu.data.pipeline import count_batches

    native = _native()
    p = tmp_path / "ws-00000"
    p.write_text(
        "1 \n"                 # label + trailing space: NOT a row
        "  1\t0:5:1\n"         # leading whitespace stripped
        "a x:y\t0:6:1\n"       # space before tab: label token is 'a x:y'
        "infx\t0:7:1\n"        # strtod inf-prefix -> label 1
        "nanjunk\t0:8:1\n"     # strtod nan-prefix -> nan > 1e-7 false -> 0
        "1\t \n"               # label + whitespace features -> stripped: row? no sep after strip -> not a row
        "0 0:9:1 \n"           # trailing space after features
    )
    cfg = DataConfig(log2_slots=12, max_nnz=4)
    py = _batches_python(str(p), cfg, 16)
    nat = _batches_native(str(p), cfg, 16)
    assert len(py) == len(nat) == 1
    for a, b in zip(py, nat):
        np.testing.assert_array_equal(a.slots, b.slots)
        np.testing.assert_array_equal(a.fields, b.fields)
        np.testing.assert_array_equal(a.mask, b.mask)
        np.testing.assert_array_equal(a.labels, b.labels)
        np.testing.assert_array_equal(a.row_mask, b.row_mask)
    assert py[0].num_rows == 5
    assert py[0].labels[0] == 1.0  # leading-whitespace row parsed
    assert py[0].labels[1] == 0.0  # 'a x:y' -> strtod 0
    assert py[0].mask[1].sum() == 1  # only 0:6:1, no phantom token from 'x:y'
    assert py[0].labels[2] == 1.0  # infx -> inf > 1e-7
    assert py[0].labels[3] == 0.0  # nanjunk -> nan; nan > 1e-7 is False
    assert count_rows(str(p)) == native.native_count_rows(str(p), 1 << 20) == 5
    assert count_batches(str(p), cfg, 16) == 1


def test_count_rows_parity(tmp_path):
    from xflow_tpu.data.libffm import count_rows
    from xflow_tpu.data.pipeline import count_batches

    native = _native()
    path = generate_shards(str(tmp_path / "s"), 1, 123, num_fields=5, ids_per_field=40, seed=8)[0]
    with open(path, "a") as f:
        f.write("\n\n1\tfoo\nbare_token\n0.5\t0:1:1")  # blanks / no-sep lines
    expected = 123 + 2  # "1\tfoo" and the final unterminated line count
    assert count_rows(path) == expected
    assert native.native_count_rows(path, 1 << 20) == expected
    # batch math incl. remainder handling
    cfg = DataConfig(log2_slots=12, max_nnz=8)
    assert count_batches(path, cfg, 32) == -(-expected // 32)
    assert len(_batches_native(path, cfg, 32)) == count_batches(path, cfg, 32)
    assert len(_batches_python(path, cfg, 32)) == count_batches(path, cfg, 32)


def test_missing_file_raises_eagerly():
    native = _native()
    with pytest.raises(FileNotFoundError):
        native.native_batch_iterator("/nonexistent.ffm", DataConfig(), 8)


def test_tiny_block_size_carry(tmp_path):
    # force many refills: block smaller than one line exercises the
    # partial-line carry path
    path = generate_shards(str(tmp_path / "s"), 1, 50, num_fields=18, ids_per_field=1000, seed=6)[0]
    cfg_small = DataConfig(log2_slots=16, max_nnz=20, block_bytes=64 * 1024)
    cfg_tiny = DataConfig(log2_slots=16, max_nnz=20, block_bytes=1)  # grows to 4096 min
    a = _batches_native(path, cfg_small, 16)
    b = _batches_native(path, cfg_tiny, 16)
    assert len(a) == len(b)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x.slots, y.slots)


def test_native_throughput_sanity(tmp_path):
    # not a perf gate — just assert the native path is meaningfully faster
    # than Python on a moderately sized file
    path = generate_shards(str(tmp_path / "s"), 1, 20000, num_fields=18, ids_per_field=5000, seed=7)[0]
    cfg = DataConfig(log2_slots=20, max_nnz=20)
    t0 = time.perf_counter()
    nb = len(_batches_native(path, cfg, 1024))
    t_native = time.perf_counter() - t0
    t0 = time.perf_counter()
    pb = len(_batches_python(path, cfg, 1024))
    t_python = time.perf_counter() - t0
    assert nb == pb
    assert t_native < t_python, (t_native, t_python)


def test_plan_sorted_wire_parity():
    """xf_plan_sorted_wire emits compact_plan_wire's dtypes directly and
    matches the int32 planner bit-for-bit (values), incl. pads; the
    wire contract violations (row >= 2^16 impossible here; non-0/1
    mask) raise loudly."""
    import numpy as np
    import pytest

    from xflow_tpu.ops.sorted_table import plan_sorted_batch

    rng = np.random.default_rng(5)
    S = 1 << 14
    slots = rng.integers(0, S, (128, 9)).astype(np.int32)
    mask = (rng.random((128, 9)) < 0.7).astype(np.float32)
    fields = rng.integers(0, 6, (128, 9)).astype(np.int32)
    a = plan_sorted_batch(slots, mask, S, fields=fields)
    b = plan_sorted_batch(slots, mask, S, fields=fields, wire=True)
    if b.sorted_row.dtype == np.int32:
        pytest.skip("native planner unavailable: wire fell back to int32")
    assert b.sorted_mask.dtype == np.uint8 and b.sorted_fields.dtype == np.uint8
    np.testing.assert_array_equal(a.sorted_slots, b.sorted_slots)
    np.testing.assert_array_equal(a.sorted_row, b.sorted_row.astype(np.int32))
    np.testing.assert_array_equal(a.sorted_mask != 0, b.sorted_mask != 0)
    np.testing.assert_array_equal(a.sorted_fields, b.sorted_fields.astype(np.int32))
    np.testing.assert_array_equal(a.win_off, b.win_off)
    bad_mask = mask.copy()
    bad_mask[0, 0] = 0.5
    with pytest.raises(ValueError, match="wire contract"):
        plan_sorted_batch(slots, bad_mask, S, fields=fields, wire=True)


def test_plan_sorted_empty_batch_matches_numpy():
    """A zero-row batch plans to the all-pad plan on BOTH planners (the
    round-5 plan_sort_core refactor briefly made the native one return
    rc=-1 because vector::data() on an empty vector is nullptr — its
    error sentinel)."""
    import numpy as np

    from xflow_tpu.ops.sorted_table import plan_sorted_batch

    S = 1 << 14
    empty = np.zeros((0, 5), np.int32)
    emptym = np.zeros((0, 5), np.float32)
    a = plan_sorted_batch(empty, emptym, S)
    assert (np.asarray(a.sorted_mask) == 0).all()
    assert (np.asarray(a.sorted_slots) == S - 1).all()
    assert a.win_off[-1] == a.sorted_slots.shape[0]
