"""SLO autotuning + batch-shape ladder tests (xflow_tpu/serve/autotune,
docs/SERVING.md "Autotuning").

Clock-injected controller units first — dominant-term steering,
hysteresis, reversal damping (no oscillation on a scripted load step),
the one-shot floor pin — then the ladder (parse/pick, exactly-once
compile per rung through the CompileRecorder, runner dispatch), the
coalescer's release-rung seam, the byte-identical-when-off pin, the
metrics_report kind="autotune" schema gate + fleet stamp separation,
the serve_bench SLO-attainment gate, the perf_ledger p99 leg, and the
CI smoke gate (tools/smoke_autotune.sh: mis-tuned start -> converges
-> BENCH_SERVE_r17.json).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from xflow_tpu.config import Config, override
from xflow_tpu.serve.autotune import (
    AUTOTUNE_KNOBS,
    AutotuneController,
    Decision,
    parse_ladder,
    pick_rung,
)
from xflow_tpu.serve.coalescer import MicroBatcher, assemble_batch

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _cfg(**extra):
    base = {
        "serve.autotune": True,
        "serve.slo_p99_ms": 20.0,
        "serve.window_ms": 10.0,
        "serve.max_batch": 64,
        "serve.autotune_band_frac": 0.15,
        "serve.autotune_step_frac": 0.5,
        "serve.autotune_min_window_ms": 0.25,
    }
    base.update(extra)
    return override(Config(), **base).serve


def _win(total, qw, dev, fill=0.5):
    return {
        "total_p99_ms": total,
        "queue_wait_p99_ms": qw,
        "device_p99_ms": dev,
        "batch_fill": fill,
    }


# ------------------------------------------------------------- controller
def test_queue_dominated_over_slo_shrinks_window():
    c = AutotuneController(_cfg(), clock=FakeClock())
    ds = c.observe(_win(30.0, 25.0, 5.0))
    assert [d.knob for d in ds] == ["window_ms"]
    assert ds[0].reason == "queue_dominated"
    assert ds[0].new < ds[0].old == 10.0
    assert c.window_ms == ds[0].new


def test_device_dominated_over_slo_steps_rung_down():
    c = AutotuneController(
        _cfg(**{"serve.ladder": "16,64"}), clock=FakeClock()
    )
    assert c.rungs == (16, 64) and c.rung == 64
    ds = c.observe(_win(30.0, 2.0, 28.0))
    assert [d.knob for d in ds] == ["rung"]
    assert ds[0].reason == "device_dominated"
    assert (ds[0].old, ds[0].new) == (64.0, 16.0) and c.rung == 16
    # at the bottom rung the window is the only remaining lever
    ds = c.observe(_win(30.0, 2.0, 28.0))
    assert [d.knob for d in ds] == ["window_ms"] and ds[0].new < 10.0


def test_hysteresis_band_holds_steady():
    c = AutotuneController(_cfg(), clock=FakeClock())
    # slo 20, band 0.15 -> [17, 23]: anything inside moves nothing
    assert c.observe(_win(20.0, 15.0, 5.0)) == []
    assert c.observe(_win(22.9, 1.0, 21.0)) == []
    assert c.observe(_win(17.1, 16.0, 1.0)) == []
    assert c.window_ms == 10.0 and c.decision_count == 0


def test_under_slo_restores_rung_then_grows_window():
    c = AutotuneController(
        _cfg(**{"serve.ladder": "16,64"}), clock=FakeClock()
    )
    c.observe(_win(30.0, 2.0, 28.0))  # rung down first
    assert c.rung == 16
    ds = c.observe(_win(5.0, 1.0, 4.0))
    assert [d.reason for d in ds] == ["rung_restore"]
    assert c.rung == 64
    ds = c.observe(_win(5.0, 1.0, 4.0))  # now device headroom grows
    assert [d.reason for d in ds] == ["device_headroom"]
    assert c.window_ms > 10.0
    # growth never passes the derived ceiling (= the SLO budget)
    for _ in range(50):
        c.observe(_win(5.0, 1.0, 4.0))
    assert c.window_ms <= c.max_window_ms == 20.0


def test_under_slo_queue_dominant_does_not_grow():
    c = AutotuneController(_cfg(), clock=FakeClock())
    # under SLO but queue-wait already dominates: growing the window
    # would hand the saved budget right back to coalescing delay
    assert c.observe(_win(10.0, 8.0, 2.0)) == []


def test_reversal_damping_converges_not_oscillates():
    c = AutotuneController(_cfg(), clock=FakeClock())
    # scripted flip-flop load: alternately over (queue) / under (device)
    # the band — an undamped multiplicative controller ping-pongs
    # forever; halving the step on each reversal must shrink the moves
    moves = []
    for i in range(20):
        w = _win(30.0, 25.0, 2.0) if i % 2 == 0 else _win(5.0, 1.0, 4.0)
        for d in c.observe(w):
            moves.append(abs(d.new - d.old))
    assert len(moves) >= 6
    # late moves are much smaller than the opening one: converging
    assert max(moves[-3:]) < 0.2 * moves[0]
    assert c.state()["step_frac"]["window_ms"] < 0.5


def test_floor_pin_warns_exactly_once_then_rearms_on_growth():
    c = AutotuneController(
        _cfg(**{"serve.window_ms": 0.25}), clock=FakeClock()
    )
    over = _win(40.0, 35.0, 5.0)
    ds = c.observe(over)
    assert [d.reason for d in ds] == ["floor_pinned"]
    assert ds[0].old == ds[0].new == 0.25  # the pin is the information
    # pinned: more over-SLO windows emit NOTHING (never flaps)
    for _ in range(5):
        assert c.observe(over) == []
    assert c.state()["floor_pinned"] is True
    # load eases -> window grows -> a NEW unattainable stretch warns again
    c.observe(_win(5.0, 1.0, 4.0))
    assert c.state()["floor_pinned"] is False
    # shrink back down to the floor, then the pin warns once more
    reasons = []
    for _ in range(20):
        reasons += [d.reason for d in c.observe(over)]
    assert reasons.count("floor_pinned") == 1


def test_observe_without_latency_evidence_steers_nothing():
    c = AutotuneController(_cfg(), clock=FakeClock())
    assert c.observe(_win(None, None, None)) == []
    assert c.observe({"batch_fill": 1.0}) == []
    assert c.windows_seen == 0


def test_controller_rejects_nonpositive_slo():
    with pytest.raises(ValueError, match="slo_p99_ms"):
        AutotuneController(_cfg(**{"serve.slo_p99_ms": 0.0}))


def test_state_snapshot_shape():
    clock = FakeClock()
    c = AutotuneController(_cfg(**{"serve.ladder": "16,64"}), clock=clock)
    c.observe(_win(30.0, 25.0, 5.0))
    clock.t = 2.0
    s = c.state()
    assert s["slo_p99_ms"] == 20.0 and s["rungs"] == [16, 64]
    assert s["windows_seen"] == 1 and s["decisions"] == 1
    assert s["since_last_decision_s"] == pytest.approx(2.0)
    assert set(s["step_frac"]) == set(AUTOTUNE_KNOBS)


# ----------------------------------------------------------------- ladder
def test_parse_ladder_shapes():
    assert parse_ladder(_cfg()) == (64,)  # "" = the pre-ladder shape
    assert parse_ladder(_cfg(**{"serve.ladder": "16,4,64"})) == (4, 16, 64)
    # rungs above max_batch clamp; max_batch always joins as the top
    assert parse_ladder(_cfg(**{"serve.ladder": "16,256"})) == (16, 64)
    with pytest.raises(ValueError, match="not an integer"):
        parse_ladder(_cfg(**{"serve.ladder": "16,big"}))
    with pytest.raises(ValueError, match=">= 1"):
        parse_ladder(_cfg(**{"serve.ladder": "0"}))


def test_pick_rung_smallest_fit():
    rungs = (16, 64, 256)
    assert pick_rung(1, rungs) == 16
    assert pick_rung(16, rungs) == 16
    assert pick_rung(17, rungs) == 64
    assert pick_rung(300, rungs) == 256  # beyond top: the top rung


# ------------------------------------------------- coalescer release rung
def _rows(n, nnz=3):
    fields = [np.arange(nnz, dtype=np.int32) for _ in range(n)]
    slots = [np.full(nnz, 7, dtype=np.int32) for _ in range(n)]
    return fields, slots


def test_release_rung_flushes_below_max_rows():
    clock = FakeClock()
    mb = MicroBatcher(max_rows=64, window_s=100.0, clock=clock)
    mb.set_release_rows(8)
    mb.submit(*_rows(4))
    assert mb.take(timeout=0.0) is None  # 4 < release rung 8
    mb.submit(*_rows(4))
    group = mb.take(timeout=0.0)  # 8 rows = the rung: size flush NOW
    assert group is not None and sum(r.num_rows for r in group) == 8


def test_release_rung_never_wedges_an_oversize_head():
    clock = FakeClock()
    mb = MicroBatcher(max_rows=64, window_s=100.0, clock=clock)
    mb.set_release_rows(8)
    # a 32-row request is legal (max_rows contract unchanged) and must
    # pop whole even though it exceeds the release rung
    mb.submit(*_rows(32))
    group = mb.take(timeout=0.0)
    assert group is not None and [r.num_rows for r in group] == [32]


def test_set_window_takes_effect_on_queued_requests():
    clock = FakeClock()
    mb = MicroBatcher(max_rows=64, window_s=100.0, clock=clock)
    mb.submit(*_rows(1))
    assert mb.take(timeout=0.0) is None
    mb.set_window_s(1.0)  # the controller shrinks the deadline
    clock.t = 1.5
    group = mb.take(timeout=0.0)
    assert group is not None and len(group) == 1


def test_release_rung_clamps_to_contract():
    mb = MicroBatcher(max_rows=64, window_s=1.0, clock=FakeClock())
    mb.set_release_rows(0)
    assert mb.release_rows == 1
    mb.set_release_rows(9999)
    assert mb.release_rows == 64


# ------------------------------------------------- runner ladder programs
@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    """One tiny trained run shared by the ladder-dispatch tests."""
    from xflow_tpu.data.synth import generate_shards
    from xflow_tpu.train.trainer import Trainer

    work = tmp_path_factory.mktemp("autotune_fixture")
    generate_shards(
        str(work / "train"), 1, 256, num_fields=5, ids_per_field=30, seed=0
    )
    cfg = _runner_cfg(
        work / "ck",
        **{"data.train_path": str(work / "train"), "train.epochs": 1,
           "train.checkpoint_every": 4},
    )
    t = Trainer(cfg)
    t.fit()
    return {"work": work}


def _runner_cfg(ckpt_dir, **extra):
    base = {
        "data.batch_size": 64,
        "data.log2_slots": 12,
        "data.max_nnz": 8,
        "model.num_fields": 5,
        "model.name": "lr",
        "train.pred_dump": False,
        "train.checkpoint_dir": str(ckpt_dir),
        "serve.max_batch": 16,
    }
    base.update(extra)
    return override(Config(), **base)


def test_ladder_compiles_each_rung_exactly_once(trained):
    from xflow_tpu.serve.runner import ServeRunner
    from xflow_tpu.telemetry import CompileRecorder

    sink: list = []
    cfg = _runner_cfg(trained["work"] / "ck", **{"serve.ladder": "4,16"})
    r = ServeRunner(cfg, recorder=CompileRecorder(sink=sink))
    r.load()
    assert r.rungs == (4, 16)
    assert r.warmup() == 2
    programs = sorted(rec["program"] for rec in sink)
    assert programs == ["predict.serve.b16", "predict.serve.b4"]
    # traffic at both rungs reuses the warmed executables: no recompile
    arrays, _ = assemble_batch([], 4, cfg.data.max_nnz)
    p, _ = r.predict(arrays)
    assert p.shape == (4,)
    arrays, _ = assemble_batch([], 16, cfg.data.max_nnz)
    p, _ = r.predict(arrays)
    assert p.shape == (16,)
    assert len(sink) == 2


def test_single_rung_keeps_pre_ladder_program_name(trained):
    """The byte-identical-off pin, compile-accounting half: no ladder
    -> ONE rung == max_batch under the ORIGINAL program name, so the
    compile stream cannot distinguish this build from a pre-ladder one."""
    from xflow_tpu.serve.runner import ServeRunner
    from xflow_tpu.telemetry import CompileRecorder

    sink: list = []
    cfg = _runner_cfg(trained["work"] / "ck")
    r = ServeRunner(cfg, recorder=CompileRecorder(sink=sink))
    r.load()
    assert r.rungs == (16,)
    assert r.warmup() == 1
    assert [rec["program"] for rec in sink] == ["predict.serve"]


def test_autotune_off_serve_stream_has_no_autotune_records(trained, tmp_path):
    """The byte-identical-off pin, telemetry half: with serve.autotune
    off (default) the app owns NO controller, and a served run's stream
    carries zero kind="autotune" records and zero autotune spans."""
    from xflow_tpu.serve.runner import ServeRunner
    from xflow_tpu.serve.server import ServeApp

    cfg = _runner_cfg(
        trained["work"] / "ck",
        **{"serve.window_ms": 1.0, "serve.metrics_every_s": 0.05,
           "serve.metrics_path": str(tmp_path / "serve.jsonl")},
    )
    runner = ServeRunner(cfg)
    runner.load()
    app = ServeApp(cfg, runner)
    assert app.autotuner is None
    assert "autotune" not in app.stats()
    app.start()
    try:
        body = json.dumps({"rows": ["0:1:1 1:2:1"]}).encode()
        for _ in range(3):
            status, _ = app.handle_predict(body)
            assert status == 200
    finally:
        app.close()
    recs = [json.loads(l) for l in open(tmp_path / "serve.jsonl")]
    assert not [r for r in recs if r.get("kind") == "autotune"]
    assert not [r for r in recs if r.get("name") == "autotune"]
    assert [r for r in recs if r.get("kind") == "serve"]


# ------------------------------------------- metrics_report autotune gate
def _metrics_report():
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    import metrics_report as mr

    return mr


def _at_rec(ts=1.0, rank=0, run_id="r1", gen=0, **kw):
    base = {
        "ts": ts, "rank": rank, "run_id": run_id, "gen": gen,
        "kind": "autotune", "knob": "window_ms", "old": 10.0, "new": 5.0,
        "reason": "queue_dominated", "slo_p99_ms": 20.0,
        "total_p99_ms": 30.0, "queue_wait_p99_ms": 25.0,
        "device_p99_ms": 5.0, "batch_fill": 0.5,
    }
    base.update(kw)
    return base


def _write(tmp_path, name, recs):
    p = tmp_path / name
    p.write_text("".join(json.dumps(r) + "\n" for r in recs))
    return str(p)


def test_check_accepts_well_formed_autotune_trail(tmp_path):
    mr = _metrics_report()
    ok = _write(tmp_path, "ok.jsonl", [
        _at_rec(ts=1.0),
        _at_rec(ts=2.0, old=5.0, new=2.5),
        _at_rec(ts=3.0, knob="rung", old=64.0, new=16.0,
                reason="device_dominated"),
    ])
    assert mr.main([ok, "--check"]) == 0


def test_check_rejects_partial_autotune_record(tmp_path):
    mr = _metrics_report()
    rec = _at_rec()
    del rec["reason"]
    assert mr.main([_write(tmp_path, "p.jsonl", [rec]), "--check"]) == 2


def test_check_rejects_unknown_knob(tmp_path):
    mr = _metrics_report()
    bad = _write(tmp_path, "k.jsonl", [_at_rec(knob="prefetch_depth")])
    assert mr.main([bad, "--check"]) == 2


def test_check_rejects_time_travel_in_decision_trail(tmp_path):
    mr = _metrics_report()
    bad = _write(tmp_path, "t.jsonl",
                 [_at_rec(ts=5.0), _at_rec(ts=1.0, old=5.0, new=2.5)])
    assert mr.main([bad, "--check"]) == 2


def test_fleet_replicas_keep_separate_autotune_trails(tmp_path):
    """Two replicas' controllers each steer their own coalescer: trails
    in separate streams with distinct (rank, replica) stamps pass; one
    stream mixing replica stamps is two controllers on one file."""
    mr = _metrics_report()
    ok = [
        _at_rec(ts=1.0, rank=0, replica=0, port=8001),
        _at_rec(ts=2.0, rank=0, replica=0, port=8001, old=5.0, new=2.5),
    ]
    ok2 = [
        _at_rec(ts=1.0, rank=1, replica=1, port=8002, old=10.0, new=5.0),
    ]
    a = _write(tmp_path, "replica0.jsonl", ok)
    b = _write(tmp_path, "replica1.jsonl", ok2)
    assert mr.main([a, b, "--check"]) == 0
    mixed = _write(tmp_path, "mixed.jsonl", [
        _at_rec(ts=1.0, rank=0, replica=0),
        _at_rec(ts=2.0, rank=0, replica=1, old=5.0, new=2.5),
    ])
    assert mr.main([mixed, "--check"]) == 2


def test_health_renders_trajectory_and_verdicts(tmp_path, capsys):
    mr = _metrics_report()
    # a converging trail: monotone shrink, no reversal churn
    good = [
        _at_rec(ts=1.0, old=25.0, new=12.5),
        _at_rec(ts=2.0, old=12.5, new=6.2),
        _at_rec(ts=3.0, old=6.2, new=3.1),
    ]
    assert mr.main([_write(tmp_path, "g.jsonl", good), "--health"]) == 0
    out = capsys.readouterr().out
    assert "autotune trajectory" in out
    assert "window_ms 25 -> 3.1" in out
    assert "[converged]" in out
    # a flip-flopping trail earns the oscillating verdict
    osc, v = [], 10.0
    for i in range(8):
        nv = v * (0.5 if i % 2 == 0 else 2.0)
        osc.append(_at_rec(ts=float(i + 1), old=v, new=nv))
        v = nv
    assert mr.main([_write(tmp_path, "o.jsonl", osc), "--health"]) == 0
    assert "[oscillating]" in capsys.readouterr().out
    # a floor-pinned trail names the unattainable SLO
    pin = [
        _at_rec(ts=1.0, old=0.5, new=0.25),
        _at_rec(ts=2.0, old=0.25, new=0.25, reason="floor_pinned"),
    ]
    assert mr.main([_write(tmp_path, "f.jsonl", pin), "--health"]) == 0
    assert "pinned at floor" in capsys.readouterr().out


# -------------------------------------------------- serve_bench + ledger
def test_transport_is_single_segment_nodelay():
    """The Nagle contract (docs/SERVING.md "Telemetry + bench"): the
    handler answers headers+body in one buffered segment with
    TCP_NODELAY per connection, and the loadgen connects NODELAY. An
    unbuffered two-write response parks every request behind the
    peer's delayed ACK — a flat ~40 ms per round trip on loopback."""
    from xflow_tpu.serve.server import _make_handler

    handler = _make_handler(None)
    assert handler.wbufsize == -1  # buffered: one segment per response
    assert handler.protocol_version == "HTTP/1.1"
    assert "setup" in vars(handler)  # the guarded TCP_NODELAY hook

    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    import serve_bench

    class _Args:
        unix = ""
        url = "http://127.0.0.1:1"
        timeout = 1.0

    conn = serve_bench._connect(_Args())
    assert isinstance(conn, serve_bench._NoDelayHTTPConnection)


def test_slo_attainment_pct():
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    import serve_bench

    lats = [0.010, 0.020, 0.030, 0.040]  # seconds
    assert serve_bench.slo_attainment_pct(lats, 25.0) == 50.0
    assert serve_bench.slo_attainment_pct(lats, 40.0) == 100.0
    assert serve_bench.slo_attainment_pct(lats, 5.0) == 0.0
    assert serve_bench.slo_attainment_pct([], 25.0) == 0.0


def test_perf_ledger_gates_serve_p99_downward(tmp_path):
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    import perf_ledger as pl

    def entries(p99_new):
        old = pl.normalize_serve("BENCH_SERVE.json", {
            "metric": "serve_qps", "value": 320.0, "p99_ms": 27.0,
            "round": 9,
        })
        # round stamp fallback: the un-suffixed baseline file joins
        # the gate via its own "round" field
        assert old and all(e["round"] == 9 for e in old)
        new = pl.normalize_serve("BENCH_SERVE_r17.json", {
            "metric": "serve_qps", "value": 700.0, "p99_ms": p99_new,
        })
        assert new and all(e["round"] == 17 for e in new)
        out = old + new
        out.sort(key=lambda e: (e["series"], str(e["metric"]),
                                e["round"] if e["round"] is not None else -1))
        return out
    # QPS doubled AND the p99 leg improved: green
    assert pl.check_regressions(entries(20.0), tol=0.2) == []
    # QPS doubled but the tail blew out: the _ms leg gates DOWNWARD
    problems = pl.check_regressions(entries(40.0), tol=0.2)
    assert any("serve_qps_p99_ms" in p for p in problems)


def test_serve_bench_attainment_rides_the_record(tmp_path):
    """--slo-ms stamps slo_ms + slo_attainment_pct into the bench JSON
    (the perf_ledger normalizer folds them); --round stamps the round."""
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    import perf_ledger as pl

    rec = {
        "metric": "serve_qps", "value": 650.0, "p99_ms": 20.0,
        "slo_ms": 27.741, "slo_attainment_pct": 99.5, "round": 17,
    }
    ent = pl.normalize_serve("BENCH_SERVE_r17.json", rec)
    head = ent[0]
    assert head["round"] == 17
    assert head["slo_attainment_pct"] == 99.5
    legs = {e["metric"] for e in ent}
    assert "serve_qps_p99_ms" in legs
    assert "serve_qps_slo_attainment_pct" in legs


# ----------------------------------------------------------- CI smoke gate
def test_smoke_autotune_script(tmp_path):
    """The autotuning CI gate end to end (tools/smoke_autotune.sh):
    train -> serve mis-tuned with the controller on -> converge under
    load (decision trail + /stats + spans) -> headline bench >= 2x the
    round-9 baseline at equal-or-better p99 -> metrics_report --check/
    --health -> perf_ledger --regress -> BENCH_SERVE_r17.json."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        ["bash", os.path.join(REPO_ROOT, "tools", "smoke_autotune.sh"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=570, env=env,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "smoke_autotune: OK" in r.stdout
    assert "converged OK" in r.stdout
    assert "headline OK" in r.stdout
    bench = json.load(open(tmp_path / "BENCH_SERVE_r17.json"))
    assert bench["metric"] == "serve_qps" and bench["round"] == 17
    assert bench["errors"] == 0
    assert bench["slo_attainment_pct"] >= 99.0
