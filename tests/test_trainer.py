import json
import os

import jax
import numpy as np
import pytest

from xflow_tpu.config import Config, override
from xflow_tpu.data.synth import generate_shards
from xflow_tpu.parallel.mesh import make_mesh
from xflow_tpu.train.trainer import Trainer
from xflow_tpu.train.checkpoint import export_sparse, latest_step


def make_cfg(tmp_path, **kw):
    base = {
        "data.train_path": str(tmp_path / "train"),
        "data.test_path": str(tmp_path / "test"),
        "data.log2_slots": 14,
        "data.batch_size": 128,
        "data.max_nnz": 12,
        "model.num_fields": 6,
        "train.epochs": 6,
        "train.log_every": 5,
    }
    base.update(kw)
    return override(Config(), **base)


@pytest.fixture
def dataset(tmp_path):
    generate_shards(str(tmp_path / "train"), 1, 1200, num_fields=6, ids_per_field=40, seed=0, noise=0.3)
    generate_shards(str(tmp_path / "test"), 1, 400, num_fields=6, ids_per_field=40, seed=99, noise=0.3, truth_seed=0)
    return tmp_path


def test_trainer_end_to_end(dataset, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    cfg = make_cfg(dataset)
    t = Trainer(cfg)
    res = t.fit()
    assert res.steps == 6 * 10  # 1200 rows / 128 → 10 batches (last padded)
    assert res.examples == 6 * 1200
    auc, ll = t.evaluate()
    assert auc > 0.8, f"auc={auc}"
    # pred dump in reference format
    lines = open("pred_0_0.txt").read().strip().split("\n")
    assert len(lines) == 400
    p, one_minus, lab = lines[0].split("\t")
    assert 0.0 <= float(p) <= 1.0 and int(one_minus) == 1 - int(lab)


def test_trainer_sharded_mesh(dataset, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    cfg = make_cfg(dataset, **{"mesh.data": 4, "mesh.table": 2, "train.epochs": 3})
    mesh = make_mesh(cfg)
    t = Trainer(cfg, mesh=mesh)
    res = t.fit()
    auc, _ = t.evaluate(dump=False)
    assert auc > 0.75, f"auc={auc}"


def test_trainer_metrics_stream(dataset, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    mpath = str(tmp_path / "metrics.jsonl")
    cfg = make_cfg(dataset, **{"train.metrics_path": mpath, "train.epochs": 2})
    Trainer(cfg).fit()
    records = [json.loads(l) for l in open(mpath)]
    assert records and all("loss" in r for r in records if "step" in r)


def test_checkpoint_resume(dataset, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    ck = str(tmp_path / "ckpt")
    cfg = make_cfg(dataset, **{"train.checkpoint_dir": ck, "train.epochs": 2})
    t1 = Trainer(cfg)
    t1.fit()
    step_saved = latest_step(ck)
    assert step_saved == 2 * 10
    # new trainer resumes and continues
    t2 = Trainer(cfg)
    assert t2.maybe_restore()
    assert int(t2.state.step) == step_saved
    np.testing.assert_allclose(
        np.asarray(t1.state.tables["w"]), np.asarray(t2.state.tables["w"])
    )
    t2.fit()
    assert int(t2.state.step) == step_saved + 2 * 10


def test_checkpoint_restore_sharded(dataset, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    ck = str(tmp_path / "ckpt")
    cfg = make_cfg(dataset, **{"train.checkpoint_dir": ck, "train.epochs": 1,
                               "mesh.data": 4, "mesh.table": 2})
    t1 = Trainer(cfg)  # unsharded save
    t1.fit()
    mesh = make_mesh(cfg)
    t2 = Trainer(cfg, mesh=mesh)  # sharded restore
    assert t2.maybe_restore()
    w = t2.state.tables["w"]
    assert len(w.addressable_shards) == 8
    np.testing.assert_allclose(np.asarray(t1.state.tables["w"]), np.asarray(w))


def test_export_sparse(dataset, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    cfg = make_cfg(dataset, **{"train.epochs": 3})
    t = Trainer(cfg)
    t.fit()
    n = t.export_sparse(str(tmp_path / "w.tsv"))
    assert n > 0
    lines = open(tmp_path / "w.tsv").read().strip().split("\n")
    assert len(lines) == n
    slot, wval = lines[0].split("\t")
    assert float(wval) != 0.0


def test_export_sparse_packed_fm(dataset, tmp_path, monkeypatch):
    """export on the LIVE packed state must emit logical slot ids and pure
    w / pure v columns (the packed [S/8, 8K] layout mixes them in storage).
    Oracle: the npz checkpoint, which always stores the logical layout."""
    monkeypatch.chdir(tmp_path)
    ck = str(tmp_path / "ckpt")
    cfg = make_cfg(dataset, **{"train.epochs": 2, "model.name": "fm",
                               "train.checkpoint_dir": ck})
    t = Trainer(cfg)
    t.fit()
    from xflow_tpu.ops.sorted_table import pack_of
    K = 1 + cfg.model.v_dim
    assert pack_of(t.state.tables["wv"], K) > 1, "state should be packed by default"

    n_w = t.export_sparse(str(tmp_path / "w.tsv"), table="w")
    n_v = t.export_sparse(str(tmp_path / "v.tsv"), table="v")
    step = latest_step(ck)
    wv_logical = np.load(os.path.join(ck, f"step_{step}", "state.npz"))["tables/wv"]
    assert wv_logical.shape[1] == K  # npz stores logical layout

    got_w = {int(l.split("\t")[0]): float(l.split("\t")[1])
             for l in open(tmp_path / "w.tsv").read().strip().split("\n")}
    want_w = {int(i): float(wv_logical[i, 0])
              for i in np.nonzero(wv_logical[:, 0])[0]}
    assert got_w == pytest.approx(want_w)
    assert n_w == len(want_w) and n_v > 0

    # v rows have v_dim columns, keyed by logical slot
    first_v = open(tmp_path / "v.tsv").readline().rstrip("\n").split("\t")
    assert len(first_v) == 1 + cfg.model.v_dim

    # calling without widths on a packed 2-D table refuses loudly
    with pytest.raises(ValueError, match="logical width"):
        export_sparse(t.state, str(tmp_path / "bad.tsv"), table="v")
