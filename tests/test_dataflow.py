"""Unit tests for the flow-sensitive dataflow engine
(xflow_tpu/analysis/dataflow.py): abstract-value joins, tuple
unpacking, loop fixpoints with freshness aging, scope-aware local-call
return propagation, and the closure/staging boundary that makes the
one-behind discipline exempt BY CONSTRUCTION — the semantics the
XF110/XF111, XF702, and retrofitted XF202 rules are built on."""

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from xflow_tpu.analysis import dataflow  # noqa: E402
from xflow_tpu.analysis.core import Module  # noqa: E402
from xflow_tpu.analysis.dataflow import (  # noqa: E402
    BOTTOM, AbsVal, Dataflow, Hooks, join, join_env,
)


def mod(src: str) -> Module:
    return Module("m.py", "m.py", src)


DEVICE = AbsVal(tags=frozenset({"device"}), fresh=True)


class TaintHooks(Hooks):
    """`make()` is a device source (ages the env); `sink()` records the
    abstract value of its argument at every call site."""

    propagate_returns = True

    def __init__(self):
        # line -> joined AbsVal: a loop body is visited once per
        # fixpoint pass, so per-site observations join (the production
        # passes get the same effect from core.run_passes' dedup)
        self._by_line: dict = {}
        self.loads = {}  # name -> last loaded AbsVal

    @property
    def sinks(self):
        return sorted(self._by_line.items())

    def at_call(self, node, callee, argvals, kwvals, env, df, fval):
        if callee == "make":
            for k, v in list(env.items()):
                if v.fresh:
                    env[k] = dataflow.replace(v, fresh=False)
            return DEVICE
        if callee == "sink" and argvals:
            cur = self._by_line.get(node.lineno)
            self._by_line[node.lineno] = argvals[0] if cur is None \
                else join(cur, argvals[0])
        return None

    def at_load(self, node, name, val, env, df):
        if name:
            self.loads[name] = val


def run(src: str, hooks=None):
    hooks = hooks or TaintHooks()
    Dataflow(mod(src), hooks).run_all()
    return hooks


# ----------------------------------------------------------------- joins


def test_join_unions_tags_and_keeps_common_identity():
    a = AbsVal(tags=frozenset({"device"}), fresh=True, spec="P('data')")
    b = AbsVal(tags=frozenset({"donated"}), spec="P('data')")
    j = join(a, b)
    assert j.tags == {"device", "donated"}
    assert j.fresh  # may-fresh: fresh on any path
    assert j.spec == "P('data')"  # agreeing identity facts survive
    assert join(a, AbsVal(spec="P('table')")).spec is None  # disagreeing don't


def test_env_join_keeps_one_sided_bindings():
    e = join_env({"x": DEVICE}, {"y": AbsVal(tags=frozenset({"loopvar"}))})
    assert e["x"].tagged("device") and e["y"].tagged("loopvar")


def test_branch_join_is_may_union():
    h = run(
        "def f(c):\n"
        "    if c:\n"
        "        x = make()\n"
        "    else:\n"
        "        x = 1\n"
        "    sink(x)\n"
    )
    (_ln, val), = h.sinks
    assert val.tagged("device")  # tainted on SOME path -> tainted


# ------------------------------------------------------------- unpacking


def test_tuple_unpack_taints_every_target():
    h = run(
        "def f(b):\n"
        "    state, m = make()\n"
        "    sink(state)\n"
        "    sink(m)\n"
    )
    assert all(v.tagged("device") for _ln, v in h.sinks)
    assert len(h.sinks) == 2


def test_literal_tuple_unpacks_elementwise():
    h = run(
        "def f(b):\n"
        "    d = make()\n"
        "    d2, host = (d, 1)\n"
        "    sink(d2)\n"
        "    sink(host)\n"
    )
    by_line = dict(h.sinks)
    assert by_line[4].tagged("device")
    assert not by_line[5].tagged("device")


def test_subscript_and_attribute_propagate_taint():
    h = run(
        "def f(b):\n"
        "    m = make()\n"
        "    sink(m['loss'])\n"
        "    sink(m.loss)\n"
        "    sink(m.sum())\n"  # method call on a tainted object
    )
    assert all(v.tagged("device") for _ln, v in h.sinks)


# ------------------------------------------------- loops, joins, freshness


def test_loop_join_reaches_fixpoint_and_ages_staleness():
    """The one-behind shape: a value staged LAST iteration is stale at
    this iteration's read (a newer dispatch aged it); the value made
    THIS iteration is fresh. Exactly the XF110 exempt/fire split."""
    h = run(
        "def f(batches):\n"
        "    staged = None\n"
        "    for b in batches:\n"
        "        m = make()\n"
        "        sink(m)\n"
        "        sink(staged)\n"
        "        staged = m\n"
    )
    by_line = dict(h.sinks)
    assert by_line[5].tagged("device") and by_line[5].fresh
    assert by_line[6].tagged("device") and not by_line[6].fresh


def test_loop_variable_carries_its_binding_loop():
    h = run(
        "def f(xs):\n"
        "    for k in xs:\n"
        "        sink(k)\n"
        "    sink(k)\n"
    )
    by_line = dict(h.sinks)
    assert by_line[3].tagged("loopvar") and by_line[3].loops
    # after the loop the fact (may-)persists, but the binding-loop ids
    # let a consumer check enclosure — the XF202 retrofit's precision
    assert by_line[4].tagged("loopvar")


def test_loopvar_killed_by_rebinding():
    h = run(
        "def f(xs):\n"
        "    for k in xs:\n"
        "        k = 3\n"
        "        sink(k)\n"
    )
    (_ln, val), = h.sinks
    assert not val.tagged("loopvar")


def test_loopvar_propagates_through_copies_and_arithmetic():
    h = run(
        "def f(xs):\n"
        "    for k in xs:\n"
        "        n = k + 1\n"
        "        sink(n)\n"
    )
    (_ln, val), = h.sinks
    assert val.tagged("loopvar")


def test_while_loop_fixpoint_terminates():
    h = run(
        "def f(c):\n"
        "    x = 0\n"
        "    while c:\n"
        "        x = make()\n"
        "        sink(x)\n"
    )
    assert h.sinks and all(v.tagged("device") for _ln, v in h.sinks)


# ------------------------------------------------- call-graph propagation


def test_local_call_return_propagates():
    h = run(
        "def produce():\n"
        "    return make()\n"
        "\n"
        "def f(b):\n"
        "    x = produce()\n"
        "    sink(x)\n"
    )
    assert any(v.tagged("device") for _ln, v in h.sinks)


def test_scope_aware_resolution_prefers_visible_def():
    """Two same-named helpers in different functions must not
    cross-link (the jit-purity precision property, now shared)."""
    h = run(
        "def a():\n"
        "    def helper():\n"
        "        return make()\n"
        "    return helper()\n"
        "\n"
        "def b():\n"
        "    def helper():\n"
        "        return 1\n"
        "    sink(helper())\n"
    )
    # b's helper is host-only: its sink must NOT see a's device value
    assert all(not v.tagged("device") for _ln, v in h.sinks)


def test_nested_def_returning_through_outer_call():
    h = run(
        "def outer():\n"
        "    def inner():\n"
        "        return make()\n"
        "\n"
        "    def use():\n"
        "        x = inner()\n"
        "        sink(x)\n"
    )
    assert any(v.tagged("device") for _ln, v in h.sinks)


def test_recursion_terminates():
    h = run(
        "def f(n):\n"
        "    if n:\n"
        "        return f(n - 1)\n"
        "    return make()\n"
        "\n"
        "def g():\n"
        "    sink(f(3))\n"
    )
    assert h.sinks  # no hang, no crash


# ------------------------------------------- closures: the staging seam


def test_closure_free_variables_are_bottom():
    """A nested function reading a value staged by its enclosing scope
    sees BOTTOM — the staging seam is the construction that exempts the
    trainer's check_pending-style one-behind closures."""
    h = run(
        "def f(batches):\n"
        "    pending = None\n"
        "    def check():\n"
        "        m, at = pending\n"
        "        sink(m)\n"
        "    for b in batches:\n"
        "        x = make()\n"
        "        check()\n"
        "        pending = (x, 1)\n"
    )
    closure_vals = [v for ln, v in h.sinks if ln == 5]
    assert closure_vals and all(not v.tagged("device")
                                for v in closure_vals)


def test_try_finally_preserves_bindings():
    """Regression pin: a try/finally with NO except handlers must not
    wipe the environment (an aliasing bug once silently dropped every
    binding made inside the try body — masking real taint downstream)."""
    h = run(
        "def f(b):\n"
        "    try:\n"
        "        x = make()\n"
        "    finally:\n"
        "        cleanup()\n"
        "    sink(x)\n"
    )
    (_ln, val), = h.sinks
    assert val.tagged("device") and val.fresh


def test_try_except_joins_handler_paths():
    h = run(
        "def f(b):\n"
        "    x = 1\n"
        "    try:\n"
        "        x = make()\n"
        "    except ValueError:\n"
        "        x = 2\n"
        "    sink(x)\n"
    )
    (_ln, val), = h.sinks
    assert val.tagged("device")


def test_fstring_and_branch_hooks_fire():
    class H(TaintHooks):
        def __init__(self):
            super().__init__()
            self.branches = []
            self.formats = []

        def at_branch(self, node, val, env, df):
            self.branches.append(val)

        def at_format(self, node, val, env, df):
            self.formats.append(val)

    h = run(
        "def f(b):\n"
        "    m = make()\n"
        "    if m:\n"
        "        pass\n"
        "    s = f'loss={m}'\n",
        H(),
    )
    assert any(v.tagged("device") for v in h.branches)
    assert any(v.tagged("device") for v in h.formats)


def test_module_level_statements_are_analyzed():
    h = run("x = make()\nsink(x)\n")
    (_ln, val), = h.sinks
    assert val.tagged("device")


# -------------------------------------- while loops: explicit fixpoints


def test_while_fixpoint_carries_back_edge_bindings():
    """A value bound at the END of a while body must be visible at the
    TOP of the next iteration (the back edge): the fixpoint's second
    pass joins it in. The engine's loop handling was written for `for`;
    this pins that `while` gets the same treatment."""
    h = run(
        "def f(c):\n"
        "    x = 1\n"
        "    while c:\n"
        "        sink(x)\n"
        "        x = make()\n"
    )
    (_ln, val), = h.sinks
    # joined across iterations: host on the first pass, device on the
    # back edge -> may-device
    assert val.tagged("device")


def test_while_one_behind_aging_matches_for_loop():
    """The XF110 exempt/fire split inside a while loop: the value made
    THIS iteration is fresh; the one staged LAST iteration was aged by
    the newer dispatch."""
    h = run(
        "def f(c):\n"
        "    staged = None\n"
        "    while c:\n"
        "        m = make()\n"
        "        sink(m)\n"
        "        sink(staged)\n"
        "        staged = m\n"
    )
    by_line = dict(h.sinks)
    assert by_line[5].tagged("device") and by_line[5].fresh
    assert by_line[6].tagged("device") and not by_line[6].fresh


def test_while_test_expression_is_evaluated_each_pass():
    """The while TEST is part of the loop body for hook purposes (the
    XF111 implicit-sync rule needs branch hooks on it) and must see the
    back-edge bindings."""
    class H(TaintHooks):
        def __init__(self):
            super().__init__()
            self.branches = []

        def at_branch(self, node, val, env, df):
            self.branches.append(val)

    h = run(
        "def f(b):\n"
        "    ok = True\n"
        "    while ok:\n"
        "        ok = make()\n",
        H(),
    )
    assert any(v.tagged("device") for v in h.branches)


def test_while_orelse_runs_after_fixpoint():
    h = run(
        "def f(c):\n"
        "    x = 1\n"
        "    while c:\n"
        "        x = make()\n"
        "    else:\n"
        "        sink(x)\n"
    )
    (_ln, val), = h.sinks
    assert val.tagged("device")


# -------------------------- comprehension / generator scope + variance


def test_comprehension_target_is_loop_variant():
    """A comprehension target varies per iteration exactly like a
    for-loop target: tagged loopvar, bound to the comprehension node
    (the XF202 enclosure check accepts comprehensions)."""
    h = run(
        "def f(xs):\n"
        "    ys = [sink(k) for k in xs]\n"
    )
    (_ln, val), = h.sinks
    assert val.tagged("loopvar") and val.loops


def test_generator_target_is_loop_variant():
    h = run(
        "def f(xs):\n"
        "    ys = list(sink(k) for k in xs)\n"
    )
    (_ln, val), = h.sinks
    assert val.tagged("loopvar") and val.loops


def test_comprehension_binding_does_not_leak_or_clobber():
    """Python gives comprehensions their own scope: the target must
    neither leak into the enclosing scope nor clobber a same-named
    outer binding."""
    h = run(
        "def f(xs):\n"
        "    k = make()\n"
        "    ys = [k + 1 for k in xs]\n"
        "    sink(k)\n"
    )
    (_ln, val), = h.sinks
    assert val.tagged("device") and not val.tagged("loopvar")


def test_comprehension_iter_taint_reaches_target():
    """Iterating a device-tainted container taints the per-element
    target (same may-semantics as the for-loop binding)."""
    h = run(
        "def f(b):\n"
        "    ms = make()\n"
        "    return [sink(m) for m in ms]\n"
    )
    (_ln, val), = h.sinks
    assert val.tagged("device") and val.tagged("loopvar")


def test_nested_comprehension_generators_chain():
    h = run(
        "def f(xss):\n"
        "    return [sink(x) for xs in xss for x in xs]\n"
    )
    (_ln, val), = h.sinks
    assert val.tagged("loopvar")


# ------------------------------------ try/except join semantics, pinned


def test_except_handler_sees_may_bindings_from_try_body():
    """The handler can run after ANY prefix of the try body: a binding
    made in the body must reach it as a may-fact (joined with the
    pre-state)."""
    h = run(
        "def f(b):\n"
        "    x = 1\n"
        "    try:\n"
        "        x = make()\n"
        "        risky()\n"
        "    except ValueError:\n"
        "        sink(x)\n"
    )
    (_ln, val), = h.sinks
    assert val.tagged("device")


def test_try_else_not_polluted_by_handler_bindings():
    """The else block runs only when NO exception fired: a handler's
    binding must not leak into it."""
    h = run(
        "def f(b):\n"
        "    x = 1\n"
        "    try:\n"
        "        x = 2\n"
        "    except Exception:\n"
        "        x = make()\n"
        "    else:\n"
        "        sink(x)\n"
    )
    (_ln, val), = h.sinks
    assert not val.tagged("device")


def test_finally_joins_body_and_handler_paths():
    """finally runs on every path: it must see the join of the body's
    and every handler's bindings, and its own bindings must survive
    into the fall-through environment."""
    h = run(
        "def f(b):\n"
        "    x = 1\n"
        "    try:\n"
        "        x = make()\n"
        "    except Exception:\n"
        "        x = 2\n"
        "    finally:\n"
        "        sink(x)\n"
        "        y = make()\n"
        "    sink(y)\n"
    )
    by_line = dict(h.sinks)
    assert by_line[8].tagged("device")  # may: device on the try path
    assert by_line[10].tagged("device")  # finally bindings fall through


def test_handler_exception_name_is_bottom():
    h = run(
        "def f(b):\n"
        "    try:\n"
        "        x = make()\n"
        "    except Exception as e:\n"
        "        sink(e)\n"
    )
    (_ln, val), = h.sinks
    assert not val.tagged("device")
