"""XF501/XF502 fixture: records drifting from docs/OBSERVABILITY.md
(never executed)."""

from xflow_tpu.jsonl import JsonlAppender


def drifted_window(app):
    app.append({
        "kind": "serve",
        "qps": 10.0,
        "queue_wait_p50ms": 1.2,  # XF501: drifted (queue_wait_p50_ms)
    })


def undocumented_kind(app):
    app.append({"kind": "shadow", "x": 1})  # XF502: no schema section


class StampedSink:
    def __init__(self, path):
        self.beats = JsonlAppender(
            path, stamp={"rank": 0, "run_id": "r", "kind": "heartbeat"}
        )

    def beat(self, step):
        # XF501: heartbeat schema has `step`/`event`, not `stepp`
        self.beats.append({"stepp": step})
