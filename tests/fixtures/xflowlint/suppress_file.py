"""File-level suppression fixture — must produce zero findings.

# xflowlint: disable-file=XF101 — fixture: this whole file opts out
"""

import time

import jax


@jax.jit
def timed(x):
    return x + time.perf_counter()


@jax.jit
def printed(x):
    print(x)
    return x
