"""XF401 fixture: misspelled config keys (never executed)."""

from xflow_tpu.config import Config, ServeConfig, override


def misspelled_attr(cfg: Config):
    return cfg.train.lag_every  # XF401: train.log_every typo


def misspelled_section(cfg: Config):
    return cfg.sreve.port  # XF401: serve typo


def misspelled_in_subtree(scfg: ServeConfig):
    return scfg.windw_ms  # XF401: serve.window_ms typo


def misspelled_override(cfg: Config):
    return override(cfg, **{"train.epocs": 3})  # XF401: train.epochs typo


CLI_ARGS = ["--set", "serve.max_bach=128"]  # XF401: serve.max_batch typo
