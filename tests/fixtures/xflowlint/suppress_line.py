"""Suppression fixture: the same XF101 violations as
bad_jit_purity.py, silenced inline — must produce zero findings."""

import time

import jax


@jax.jit
def timed(x):
    t0 = time.perf_counter()  # xflowlint: disable=XF101 — fixture: intentional
    return x + t0
