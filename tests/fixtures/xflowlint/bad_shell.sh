#!/usr/bin/env bash
# XF601 + XF401 fixture: no pipefail, and a misspelled --set key.
set -eu

python -m xflow_tpu train --set train.log_evry=10  # XF401: log_every typo
