"""Negative fixture: idiomatic patterns near every rule's boundary —
must produce zero findings (never executed)."""

import threading
import time

import jax

from xflow_tpu.config import Config


@jax.jit
def pure_step(x):
    # jax.debug.print is the sanctioned escape hatch
    jax.debug.print("x = {}", x)
    return x * 2


def host_timing(xs):
    # timers OUTSIDE the traced function are the PR 2 idiom
    t0 = time.perf_counter()
    y = pure_step(xs)
    return y, time.perf_counter() - t0


def valid_config_reads(cfg: Config):
    return cfg.train.log_every, cfg.serve.window_ms, cfg.num_slots


class SingleThreadedCounter:
    """No thread spawn -> the lockset pass must not analyze it."""

    def __init__(self):
        self.n = 0

    def bump(self):
        self.n += 1  # single-threaded mutation is fine


class LockedWorker:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        with self._lock:
            self._n += 1

    def read(self):
        with self._lock:
            return self._n


def documented_record(app):
    app.append({"kind": "serve", "event": "start"})
