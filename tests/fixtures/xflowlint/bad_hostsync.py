"""XF110/XF111 fixture: host-sync taint in the hot loops (never run).

Each marked line blocks the hot path on a device value dispatched in
the SAME iteration — the sync-bubble class the one-step-behind
StepTimer discipline exists to remove. The unmarked `staged` reads at
the bottom of the fit loop are the DELIBERATE one-behind pattern and
must stay silent: a newer dispatch has aged them, so the block hides
under its device time (exemption by construction, not suppression).
"""

import jax
import numpy as np


class _Trainer:
    def _fit(self, batches):
        state = object()
        staged = None
        for batch in batches:
            state, m = self.train_step(state, batch)
            loss = float(m["loss"])  # XF110: same-iteration loss read
            print(m["rows"])  # XF110: print forces the transfer
            if m["update_ok"]:  # XF111: implicit bool sync in a branch
                continue
            note = f"grad={m['grad_norm']}"  # XF110: f-string interpolation
            self.log(loss, note)
            # one-behind: staged LAST iteration, aged by this
            # iteration's dispatch — reading it here is the sanctioned
            # discipline and must NOT fire
            if staged is not None:
                self.emit(float(staged["loss"]))
            staged = m
        # post-run epilogue: this loop dispatches NOTHING, so its
        # blocking reads are mandatory one-time syncs, not bubbles —
        # exempt by construction (only dispatching loops can stall)
        for key in ("loss", "rows"):
            self.emit(float(m[key]))


class _Server:
    def __init__(self, make_step):
        self.eval_step = make_step()
        self.out = []

    def _worker_loop(self):
        while True:
            group = self.take()
            p = self.eval_step(group)
            self.out.append(np.asarray(p))  # XF110: same-iteration readback
            if bool(p.sum()):  # XF110: bool() blocks on the batch
                break


def prefetch(iterator, q):
    for item in iterator:
        dev = jax.device_put(item)
        q.put(int(dev[0]))  # XF110: int() blocks on the fresh transfer
