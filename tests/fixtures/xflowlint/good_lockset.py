"""XF301 negative fixture: the POST-PR 8 shape — same threads, same
mutations, every write under the append lock. Must stay silent."""

import json
import threading
import time


class LockedFleetAppender:
    def __init__(self, path: str):
        self._path = path
        self._f = None
        self._size = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True
        )
        self._health_thread.start()

    def _health_loop(self):
        while not self._stop.wait(0.5):
            self.append({"kind": "serve", "event": "health"})

    def handle_request(self, record: dict):
        self.append({"kind": "serve", **record})

    def append(self, record: dict):
        if not self._path:
            return
        with self._lock:
            if self._f is None:
                self._f = open(self._path, "a")
            rec = {"ts": round(time.time(), 6), **record}
            line = json.dumps(rec) + "\n"
            self._f.write(line)
            self._f.flush()
            self._size += len(line)

    def close(self):
        self._stop.set()
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None
