"""XF301 fixture: the pre-PR 8 unlocked JsonlAppender, reproduced in
its first multi-threaded caller (never executed).

Before PR 8, `xflow_tpu/jsonl.py JsonlAppender.append` had no lock —
written for the single-threaded trainer. The serving-fleet router then
called one appender from request-handler threads AND its health loop
at once, and two `write()` calls could interleave two records into one
damaged JSONL line. This file is the pre-fix `append`/`close` bodies
(lazy open, stamp fold, write+flush — no `self._lock`) inside a
router-shaped class that spawns the health thread; the lockset pass
must flag the `_f`/`_size`/`_static` mutations forever.
"""

import json
import os
import threading
import time


class UnlockedFleetAppender:
    """Pre-PR 8 appender + the PR 8 caller shape that broke it."""

    def __init__(self, path: str):
        self._path = path
        self._f = None
        self._size = 0
        self._static = None
        self._stop = threading.Event()
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True
        )
        self._health_thread.start()

    # ---- the health loop: one writer thread -------------------------
    def _health_loop(self):
        while not self._stop.wait(0.5):
            self.append({"kind": "serve", "event": "health"})

    # ---- the request handlers: N more writer threads ----------------
    def handle_request(self, record: dict):
        self.append({"kind": "serve", **record})

    # ---- the PRE-FIX append: no lock anywhere -----------------------
    def append(self, record: dict):
        if not self._path:
            return
        if self._f is None:
            parent = os.path.dirname(self._path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            self._f = open(self._path, "a")  # unlocked lazy open
        if self._static is None:
            self._static = {"rank": 0, "run_id": "fixture"}
        rec = {"ts": round(time.time(), 6), **self._static, **record}
        line = json.dumps(rec) + "\n"
        self._f.write(line)  # two threads here = one damaged line
        self._f.flush()
        self._size += len(line)

    def close(self):
        self._stop.set()
        if self._f is not None:
            self._f.close()
            self._f = None
