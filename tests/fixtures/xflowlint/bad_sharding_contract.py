"""XF701/XF702/XF703 fixture: sharding-contract violations (never run).

The XF704 cross-engine checks need several engine builders in one
source set, so they are exercised by the scratch-tree drills in
tools/smoke_lint.sh and tests/test_xflowlint.py instead of a fixture.
"""

import jax
from jax.sharding import NamedSharding, PartitionSpec as P


def undeclared_axis(mesh):
    # the mesh declares ('data', 'table') — this fails inside GSPMD
    # partitioning at run time, in lint now
    return NamedSharding(mesh, P("tabel", None))  # XF701: misspelled axis


def donated_read(step_fn, state, batch):
    jitted = jax.jit(step_fn, donate_argnums=(0,))
    new_state = jitted(state, batch)
    # works on CPU test runs, corrupts/crashes on TPU: the donated
    # buffer was invalidated by the call above
    return state, new_state  # XF702: donated buffer read


def undonated_train_step():
    def train_step(state, batch):
        return state

    return jax.jit(train_step)  # XF703: train-step jit without donation
