"""XF101 fixture: host effects inside traced code (never executed)."""

import random
import time

import jax
import jax.lax as lax
import numpy as np

COUNT = 0


@jax.jit
def step(x):
    t0 = time.perf_counter()  # XF101: host timer freezes at trace time
    print("stepping", x)  # XF101: prints once per compile
    return x * random.random() + t0  # XF101: host RNG


def scan_body(carry, x):
    np.random.seed(0)  # XF101: reached via lax.scan body
    return carry + x, x


def outer(xs):
    return lax.scan(scan_body, 0.0, xs)


def impure_helper():
    global COUNT  # XF101: global mutation, reached from a jit root
    COUNT += 1


@jax.jit
def uses_helper(x):
    impure_helper()
    return x


def traced_lambda(xs):
    return jax.jit(lambda v: v + time.time())(xs)  # XF101: lambda body
