"""XF201/XF202/XF203 fixture: jit-cache thrash patterns (never run)."""

import jax


def f(x, n):
    return x * n


def jit_in_loop(xs):
    out = []
    for x in xs:
        out.append(jax.jit(f)(x, 2))  # XF201: fresh callable per iteration
    return out


g = jax.jit(f, static_argnums=(1,))


def unhashable_static(x):
    return g(x, [1, 2])  # XF203: list literal in a static slot


def varying_static(x):
    a = g(x, 3)  # XF202: 3 vs 4 below — one compile per value
    b = g(x, 4)
    return a + b


def loop_var_static(x):
    total = x
    for k in range(8):
        total = g(total, k)  # XF202: loop variable in a static slot
    return total
