"""Serving-fleet resilience tests (serve/router.py, serve/fleet.py,
brownout admission control, docs/SERVING.md "Fleet").

Socket-free core first — the circuit-breaker state machine and the
brownout mode on injectable clocks — then the router against FAKE
replicas (tiny stdlib HTTP servers with scriptable behavior: no
checkpoint, no jax anywhere near the routing tests), the drain
ordering, the serve_bench client knobs, the metrics_report fleet
identity gates, and the CI chaos drill (tools/smoke_serve_fleet.sh:
3 replicas, SIGKILL one mid-bench, corrupt a checkpoint mid-reload,
zero failed client requests)."""

import json
import os
import socket
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from xflow_tpu.serve.coalescer import (
    BrownoutPolicy,
    MicroBatcher,
    RejectedRequest,
)
from xflow_tpu.serve.router import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    Backend,
    CircuitBreaker,
    ConnectError,
    Router,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


# ------------------------------------------------------- circuit breaker
def test_breaker_opens_after_k_consecutive_failures():
    clock = FakeClock()
    br = CircuitBreaker(fail_threshold=3, open_s=5.0, clock=clock)
    assert br.state == CLOSED and br.allow()
    assert br.record_failure() is False
    assert br.record_failure() is False
    # the tripping failure reports True exactly once (one event)
    assert br.record_failure() is True
    assert br.state == OPEN and not br.allow()
    assert br.opened_count == 1


def test_breaker_success_resets_consecutive_count():
    br = CircuitBreaker(fail_threshold=2, clock=FakeClock())
    br.record_failure()
    br.record_success()
    br.record_failure()
    assert br.state == CLOSED  # 1+1 non-consecutive failures never trip


def test_breaker_half_open_probe_accounting():
    clock = FakeClock()
    br = CircuitBreaker(fail_threshold=1, open_s=5.0, clock=clock)
    br.record_failure()
    assert br.state == OPEN
    assert not br.allow_probe()  # OPEN holds: no probe before open_s
    clock.t = 5.1
    assert br.state == HALF_OPEN
    assert not br.allow()  # real traffic still fenced off
    assert br.allow_probe()  # exactly ONE probe permit...
    assert not br.allow_probe()  # ...while it is in flight
    br.record_success()
    assert br.state == CLOSED and br.allow()


def test_breaker_stale_success_cannot_close_an_open_circuit():
    # a forward launched BEFORE the trip completes after it: the
    # breaker opened on fresher evidence, so the straggler's 200 must
    # not skip the open_s hold — recovery goes through the probe
    clock = FakeClock()
    br = CircuitBreaker(fail_threshold=1, open_s=5.0, clock=clock)
    br.record_failure()
    assert br.state == OPEN
    assert br.record_success() is False  # stale: refused
    assert br.state == OPEN
    clock.t = 5.1
    assert br.allow_probe()
    assert br.record_success(probe=True) is True  # the probe closes it
    assert br.state == CLOSED


def test_breaker_stale_failure_cannot_reopen_a_half_open_circuit():
    # the mirror of the stale-success guard: a forward launched BEFORE
    # the trip that fails during the HALF_OPEN window is evidence about
    # the OLD process — it must not steal the probe permit or restart
    # the open_s timer (each straggler would delay rejoin by open_s)
    clock = FakeClock()
    br = CircuitBreaker(fail_threshold=1, open_s=5.0, clock=clock)
    br.record_failure()
    clock.t = 5.1
    assert br.state == HALF_OPEN
    assert br.allow_probe()  # the real probe is in flight
    assert br.record_failure() is False  # straggler fails now: ignored
    assert br.state == HALF_OPEN
    assert br.record_success(probe=True) is True  # probe still closes it
    assert br.state == CLOSED


def test_breaker_failed_probe_reopens_with_fresh_timer():
    clock = FakeClock()
    br = CircuitBreaker(fail_threshold=1, open_s=5.0, clock=clock)
    br.record_failure()
    clock.t = 5.1
    assert br.allow_probe()
    # re-open is not a new trip event
    assert br.record_failure(probe=True) is False
    assert br.state == OPEN
    clock.t = 10.0  # 4.9s after the re-open: timer restarted
    assert br.state == OPEN
    clock.t = 10.3
    assert br.state == HALF_OPEN and br.allow_probe()


# ------------------------------------------------------------- brownout
def _mb(clock, **kw):
    policy = BrownoutPolicy(
        high_rows=8, low_rows=2, after_s=1.0, window_factor=0.25
    )
    events = []
    mb = MicroBatcher(
        max_rows=4, window_s=8.0, max_queue_rows=100, clock=clock,
        brownout=policy,
        on_brownout=lambda active, q: events.append((active, q)),
        **kw,
    )
    return mb, events


def _rows(n, nnz=2):
    import numpy as np

    return (
        [np.arange(nnz, dtype=np.int32) for _ in range(n)],
        [np.full(nnz, 3, dtype=np.int32) for _ in range(n)],
    )


def test_brownout_enters_on_sustained_backlog_and_sheds_low_priority():
    clock = FakeClock()
    mb, events = _mb(clock)
    for _ in range(3):  # 9 rows queued >= high_rows=8
        mb.submit(*_rows(3))
    assert not mb.brownout  # over the line but not SUSTAINED yet
    clock.t = 1.1
    mb.submit(*_rows(1))  # the submit that observes the sustain window
    assert mb.brownout
    assert events == [(True, 10)]
    # low priority sheds with a retryable 503-class rejection...
    with pytest.raises(RejectedRequest, match="brownout") as ei:
        mb.submit(*_rows(1), priority=-1)
    assert ei.value.shed and not ei.value.client_error
    # ...normal priority still queues (the hard cliff is far away)
    mb.submit(*_rows(1), priority=0)
    assert mb.queued_rows == 11


def test_brownout_shrinks_the_coalescing_window():
    clock = FakeClock()
    mb, _ = _mb(clock)
    for _ in range(4):
        mb.submit(*_rows(3))
    clock.t = 1.1
    mb.submit(*_rows(3))  # sustained over high_rows: brownout enters
    assert mb.brownout
    # drain down to exactly the t=1.1 request (3 rows > low_rows=2, so
    # the exit timer never starts while we measure)
    while mb.queued_rows > 3:
        assert mb.take(timeout=0.0) is not None
    # its deadline flush: full window = 1.1 + 8s = 9.1; brownout window
    # = 1.1 + 8 * 0.25 = 3.1
    clock.t = 2.5
    assert mb.take(timeout=0.0) is None  # < 3.1: still coalescing
    clock.t = 3.2
    group = mb.take(timeout=0.0)
    assert group is not None  # the SHRUNK window flushed, not the 8s one
    assert mb.brownout  # still in brownout throughout the measurement


def test_brownout_exits_after_sustained_drain_with_hysteresis():
    clock = FakeClock()
    mb, events = _mb(clock)
    for _ in range(4):
        mb.submit(*_rows(3))
    clock.t = 1.1
    mb.submit(*_rows(1))  # 13 rows; brownout on
    assert mb.brownout
    while mb.take(timeout=0.0) is not None:
        pass
    assert mb.queued_rows == 0  # drained below low_rows=2...
    assert mb.brownout  # ...but not sustained yet (hysteresis)
    clock.t = 2.5
    assert mb.take(timeout=0.0) is None  # an idle take observes the exit
    assert not mb.brownout
    assert events == [(True, 13), (False, 0)]


def test_parse_priority_header():
    from xflow_tpu.serve.server import parse_priority

    assert parse_priority("low") == -1
    assert parse_priority(" LOW ") == -1
    assert parse_priority("normal") == 0
    assert parse_priority(None) == 0


# ------------------------------------------------------------ serve faults
def test_serve_faults_from_env(monkeypatch):
    from xflow_tpu.testing.faults import serve_faults_from_env

    assert serve_faults_from_env() == (0.0, 0)
    monkeypatch.setenv("XFLOW_FAULT_SERVE_DELAY_S", "0.25")
    monkeypatch.setenv("XFLOW_FAULT_SERVE_KILL_BATCHES", "7")
    assert serve_faults_from_env() == (0.25, 7)
    # replica-gated: wrong replica sees nothing
    monkeypatch.setenv("XFLOW_FAULT_SERVE_REPLICA", "1")
    assert serve_faults_from_env() == (0.0, 0)
    monkeypatch.setenv("XFLOW_REPLICA", "1")
    assert serve_faults_from_env() == (0.25, 7)
    # generation-gated kill: the supervised relaunch must survive
    monkeypatch.setenv("XFLOW_RESTART_GEN", "1")
    assert serve_faults_from_env() == (0.25, 0)


# ---------------------------------------------------------- fake replicas
class FakeReplica:
    """A scriptable stand-in for one `xflow serve` replica: answers the
    same /predict + /healthz wire protocol with a configurable mode —
    ok | shed (503) | slow (ok after delay_s) | broken (500, the
    device-error path: healthz still 200) — so routing policy is
    testable with no checkpoint or device anywhere."""

    def __init__(self, mode="ok", delay_s=0.0, step=20):
        self.mode = mode
        self.delay_s = delay_s
        self.step = step
        self.predicts = 0
        self.healthz = 0
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _reply(self, status, payload):
                data = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_POST(self):
                outer.predicts += 1
                n = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(n)) if n else {}
                rows = body.get("rows", [])
                if outer.mode == "shed":
                    self._reply(503, {"error": "queue full; retry later"})
                    return
                if outer.mode == "broken":
                    self._reply(500, {"error": "RuntimeError: device"})
                    return
                if outer.mode == "slow":
                    time.sleep(outer.delay_s)
                self._reply(200, {
                    "pctr": [0.5] * len(rows),
                    "generation": 1,
                    "step": outer.step,
                    "replica_mode": outer.mode,
                })

            def do_GET(self):
                outer.healthz += 1
                # a shedding replica is still ALIVE (healthz 200): the
                # router retries its 503s elsewhere but never ejects it
                self._reply(200, {"ok": True, "step": outer.step})

            def log_message(self, fmt, *args):
                pass

        class Server(ThreadingHTTPServer):
            daemon_threads = True

        self.srv = Server(("127.0.0.1", 0), Handler)
        self.port = self.srv.server_address[1]
        self.thread = threading.Thread(
            target=self.srv.serve_forever, daemon=True
        )
        self.thread.start()

    def close(self):
        self.srv.shutdown()
        self.srv.server_close()


def _dead_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port  # nothing listens here


def _router(replicas, tmp_path=None, **kw):
    backends = [
        Backend(i, "127.0.0.1", r if isinstance(r, int) else r.port,
                breaker=CircuitBreaker(
                    fail_threshold=kw.pop("fail_threshold", 3)
                    if "fail_threshold" in kw else 3,
                    open_s=kw.pop("open_s", 60.0) if "open_s" in kw else 60.0,
                ))
        for i, r in enumerate(replicas)
    ]
    from xflow_tpu.jsonl import JsonlAppender

    app = JsonlAppender(
        str(tmp_path / "router.jsonl") if tmp_path else "",
        stamp={"rank": -1, "run_id": "fleet-test"},
    )
    kw.setdefault("health_poll_s", 30.0)  # default: health loop inert
    return Router(backends, appender=app, **kw)


BODY = json.dumps({"rows": ["0:a 1:b"]}).encode()


def test_router_round_robins_across_healthy():
    reps = [FakeReplica(), FakeReplica()]
    r = _router(reps)
    try:
        for _ in range(6):
            status, data = r.handle_predict(BODY)
            assert status == 200
        assert reps[0].predicts == 3 and reps[1].predicts == 3
    finally:
        r.close()
        for rep in reps:
            rep.close()


def test_router_retries_503_on_a_different_replica():
    reps = [FakeReplica(mode="shed"), FakeReplica()]
    r = _router(reps, retries=2, deadline_ms=5000)
    try:
        for _ in range(4):
            status, data = r.handle_predict(BODY)
            assert status == 200, data
            assert json.loads(data)["replica_mode"] == "ok"
        assert r.stats["retries"] >= 1
    finally:
        r.close()
        for rep in reps:
            rep.close()


def test_router_never_ejects_a_shedding_replica():
    # a 503 is an ANSWER — the replica is alive, just shedding; feeding
    # it to the breaker would amplify a fleet-wide brownout into a
    # total "no healthy replica" outage for normal-priority traffic
    reps = [FakeReplica(mode="shed"), FakeReplica()]
    r = _router(reps, retries=2, deadline_ms=5000, fail_threshold=2)
    try:
        for _ in range(8):
            assert r.handle_predict(BODY)[0] == 200
        assert r.backends[0].breaker.state == CLOSED
        assert len(r.healthy()) == 2
    finally:
        r.close()
        for rep in reps:
            rep.close()


def test_router_retries_and_ejects_a_persistent_500_replica(tmp_path):
    # a non-503 5xx is the replica FAILING the request (device error,
    # broken tables) while its /healthz can still say 200 — the router
    # must retry it elsewhere AND feed the breaker, or 1/N of all
    # traffic round-robins into permanent 500s forever
    reps = [FakeReplica(mode="broken"), FakeReplica()]
    r = _router(reps, tmp_path=tmp_path, retries=2, deadline_ms=5000,
                fail_threshold=2)
    try:
        for _ in range(6):
            status, data = r.handle_predict(BODY)
            assert status == 200, data
            assert json.loads(data)["replica_mode"] == "ok"
        assert r.backends[0].breaker.state == OPEN
        assert [b.idx for b in r.healthy()] == [1]
        from xflow_tpu.jsonl import read_jsonl

        opens = [rec for rec in read_jsonl(str(tmp_path / "router.jsonl"))
                 if rec.get("event") == "circuit_open"]
        assert opens and opens[0]["reason"] == "http_500"
    finally:
        r.close()
        for rep in reps:
            rep.close()


def test_backend_flushes_keepalive_pool_on_connect_failure():
    # a SIGKILLed replica leaves dead keep-alive sockets in the pool;
    # each would burn one half-open probe and re-open the circuit,
    # stalling the restarted replica's rejoin by open_s per socket
    import http.client

    port = _dead_port()
    b = Backend(0, "127.0.0.1", port)
    try:
        for _ in range(3):  # the stale keep-alives the kill left behind
            b._put_conn(http.client.HTTPConnection("127.0.0.1", port))
        assert len(b._pool) == 3
        with pytest.raises(ConnectError):
            b.request("POST", "/predict", BODY, timeout=1.0)
        assert len(b._pool) == 0
    finally:
        b.close()


def test_router_failovers_counts_only_backend_switches():
    # one shedding replica is the only choice: retries re-land on it,
    # so retries climbs but failovers (actual backend SWITCHES) stays 0
    rep = FakeReplica(mode="shed")
    r = _router([rep], retries=2, deadline_ms=5000)
    try:
        status, _ = r.handle_predict(BODY)
        assert status == 503
        assert r.stats["retries"] == 2
        assert r.stats["failovers"] == 0
    finally:
        r.close()
        rep.close()
    # with somewhere else to go, the retry IS a failover (round-robin:
    # some first attempts land on the shedder and switch away)
    reps = [FakeReplica(mode="shed"), FakeReplica()]
    r = _router(reps, retries=2, deadline_ms=5000)
    try:
        for _ in range(4):
            assert r.handle_predict(BODY)[0] == 200
        assert r.stats["failovers"] >= 1
        assert r.stats["failovers"] == r.stats["retries"]
    finally:
        r.close()
        for rep in reps:
            rep.close()


def test_router_fails_over_a_dead_replica_and_ejects_it(tmp_path):
    reps = [_dead_port(), FakeReplica()]
    r = _router(reps, tmp_path=tmp_path, retries=2, deadline_ms=5000,
                fail_threshold=2)
    try:
        for _ in range(4):
            status, _ = r.handle_predict(BODY)
            assert status == 200
        # 2 consecutive connect failures ejected backend 0
        assert r.backends[0].breaker.state == OPEN
        assert [b.idx for b in r.healthy()] == [1]
        # post-ejection requests never touch the dead one (no retries)
        before = r.stats["retries"]
        for _ in range(3):
            assert r.handle_predict(BODY)[0] == 200
        assert r.stats["retries"] == before
        from xflow_tpu.jsonl import read_jsonl

        events = [rec["event"] for rec in read_jsonl(str(tmp_path / "router.jsonl"))]
        assert "circuit_open" in events
    finally:
        r.close()
        reps[1].close()


def test_router_circuit_recovers_via_half_open_probe(tmp_path):
    rep = FakeReplica()
    dead = _dead_port()
    r = _router([dead, rep], tmp_path=tmp_path, retries=2,
                fail_threshold=1, open_s=0.2, health_poll_s=0.1)
    r.start()
    try:
        # round-robin alternates; within two requests one lands on the
        # dead backend, trips it (fail_threshold=1), and fails over
        for _ in range(2):
            assert r.handle_predict(BODY)[0] == 200
        assert r.backends[0].breaker.state in (OPEN, HALF_OPEN)
        # resurrect "replica 0" at the same port — like a supervised
        # fleet restart rebinding its fixed port
        revived = None
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if revived is None:
                try:
                    # bind a real FakeReplica onto the SAME port
                    revived = _fake_on_port(dead)
                except OSError:
                    time.sleep(0.05)
                    continue
            if r.backends[0].breaker.state == CLOSED:
                break
            time.sleep(0.05)
        assert r.backends[0].breaker.state == CLOSED
        from xflow_tpu.jsonl import read_jsonl

        events = [rec["event"] for rec in read_jsonl(str(tmp_path / "router.jsonl"))]
        assert "circuit_close" in events
    finally:
        r.close()
        rep.close()
        if revived is not None:
            revived.close()


def _fake_on_port(port: int) -> FakeReplica:
    """A FakeReplica bound to a specific port (the revival drill)."""
    rep = FakeReplica.__new__(FakeReplica)
    rep.mode, rep.delay_s, rep.step = "ok", 0.0, 20
    rep.predicts = rep.healthz = 0
    outer = rep

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def _reply(self, status, payload):
            data = json.dumps(payload).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_POST(self):
            outer.predicts += 1
            self._reply(200, {"pctr": [0.5], "generation": 1, "step": 20})

        def do_GET(self):
            outer.healthz += 1
            self._reply(200, {"ok": True})

        def log_message(self, fmt, *args):
            pass

    class Server(ThreadingHTTPServer):
        daemon_threads = True
        allow_reuse_address = True

    rep.srv = Server(("127.0.0.1", port), Handler)
    rep.port = port
    rep.thread = threading.Thread(target=rep.srv.serve_forever, daemon=True)
    rep.thread.start()
    return rep


def test_router_hedges_a_slow_replica(tmp_path):
    slow = FakeReplica(mode="slow", delay_s=1.5)
    fast = FakeReplica()
    r = _router([slow, fast], tmp_path=tmp_path, retries=1,
                deadline_ms=10000, hedge_ms=100)
    try:
        # force the slow one primary: round-robin picks index (rr+1)%2
        wins = 0
        for _ in range(4):
            t0 = time.perf_counter()
            status, data = r.handle_predict(BODY)
            assert status == 200
            if time.perf_counter() - t0 < 1.0:
                wins += 1
        # at least the requests routed to the slow primary hedged fast
        assert r.stats["hedges"] >= 1
        assert r.stats["hedge_wins"] >= 1
        assert wins >= 1
    finally:
        r.close()
        slow.close()
        fast.close()


def test_router_retry_exhaustion_is_an_honest_503():
    # every retry burns on a fast fleet-wide shed with budget to spare:
    # counted retries_exhausted, NOT deadline_exceeded (the two signals
    # need opposite operator fixes — bigger budget vs more capacity)
    reps = [FakeReplica(mode="shed"), FakeReplica(mode="shed")]
    r = _router(reps, retries=5, deadline_ms=5000, fail_threshold=100)
    try:
        status, data = r.handle_predict(BODY)
        assert status == 503
        assert r.stats["retries"] > 0
        assert r.stats["retries_exhausted"] == 1
        assert r.stats["deadline_exceeded"] == 0
    finally:
        r.close()
        for rep in reps:
            rep.close()


def test_router_no_healthy_backend_is_503():
    r = _router([_dead_port()], retries=0, fail_threshold=1)
    try:
        assert r.handle_predict(BODY)[0] == 503  # connect fails, trips
        status, data = r.handle_predict(BODY)
        assert status == 503
        assert b"no healthy replica" in data
        assert r.stats["no_backend"] >= 1
    finally:
        r.close()


# ---------------------------------------------------------------- drain
def test_router_drain_finishes_inflight_then_rejects(tmp_path):
    slow = FakeReplica(mode="slow", delay_s=0.8)
    r = _router([slow], tmp_path=tmp_path, deadline_ms=10000)
    results = []
    try:
        t = threading.Thread(
            target=lambda: results.append(r.handle_predict(BODY))
        )
        t.start()
        time.sleep(0.2)  # request is in flight at the replica
        assert r.drain(timeout_s=10.0) is True  # waits it out
        t.join(timeout=10)
        assert results and results[0][0] == 200  # the admitted one FINISHED
        # post-drain arrivals are refused (retryable — the LB's cue)
        assert r.handle_predict(BODY)[0] == 503
        from xflow_tpu.jsonl import read_jsonl

        events = [rec["event"] for rec in read_jsonl(str(tmp_path / "router.jsonl"))]
        assert "drain" in events
    finally:
        r.close()
        slow.close()


def test_drain_fleet_orders_router_before_replicas():
    from xflow_tpu.serve.fleet import drain_fleet

    calls = []

    class FakeRouter:
        def drain(self, timeout_s=30.0):
            calls.append("router_drain")
            return True

    class FakeSup:
        def __init__(self, i):
            self.i = i

        def terminate(self, sig=None):
            calls.append(f"terminate_{self.i}")

    import io

    assert drain_fleet(FakeRouter(), [FakeSup(0), FakeSup(1)],
                       out=io.StringIO()) is True
    # THE ordering: no replica dies before the router finished draining
    assert calls == ["router_drain", "terminate_0", "terminate_1"]


def test_replica_env_contract():
    from xflow_tpu.serve.fleet import replica_env

    env = replica_env({"PATH": "/bin"}, idx=2, port=9003, run_id="r1",
                      gen=3, stagger_s=0.5, world=3)
    assert env["XFLOW_REPLICA"] == "2"
    assert env["XFLOW_REPLICA_PORT"] == "9003"
    assert env["XFLOW_PROCESS_ID"] == "2"
    assert env["XFLOW_NUM_PROCESSES"] == "3"  # fleet world = replica count
    assert env["XFLOW_RESTART_GEN"] == "3"
    assert env["XFLOW_RUN_ID"] == "r1"
    assert env["XFLOW_RELOAD_STAGGER_S"] == "1.0"  # idx * stagger
    assert env["JAX_PLATFORMS"] == "cpu"  # replicas default off-device
    assert env["PATH"] == "/bin"


def test_checkpoint_watcher_staggers_the_reload():
    """The staggered-reload contract: replica k's watcher delays acting
    on a NOTICED newer step by its stagger share, so a fleet never
    pauses every replica on one checkpoint swap at once."""
    from xflow_tpu.serve.runner import CheckpointWatcher

    class FakeRunner:
        def __init__(self):
            self.step = 4
            self.reloaded_at = None

        def latest_committed_step(self):
            return 8

        def maybe_reload(self):
            self.reloaded_at = time.monotonic()
            self.step = 8

            class G:
                step, gen = 8, 2

            return G()

    fast, slow = FakeRunner(), FakeRunner()
    t0 = time.monotonic()
    w0 = CheckpointWatcher(fast, poll_s=0.05, stagger_s=0.0)
    w2 = CheckpointWatcher(slow, poll_s=0.05, stagger_s=0.6)
    w0.start()
    w2.start()
    try:
        deadline = time.monotonic() + 10
        while (fast.reloaded_at is None or slow.reloaded_at is None) and \
                time.monotonic() < deadline:
            time.sleep(0.02)
    finally:
        w0.close()
        w2.close()
    assert fast.reloaded_at is not None and slow.reloaded_at is not None
    # replica 0 swaps promptly; replica 2 holds its stagger share
    assert slow.reloaded_at - t0 >= 0.6
    assert slow.reloaded_at - fast.reloaded_at >= 0.3


# ----------------------------------------------------- jsonl replica stamp
def test_jsonl_stamps_replica_identity(tmp_path, monkeypatch):
    from xflow_tpu.jsonl import JsonlAppender, read_jsonl

    monkeypatch.setenv("XFLOW_REPLICA", "2")
    monkeypatch.setenv("XFLOW_REPLICA_PORT", "9002")
    p = tmp_path / "a.jsonl"
    app = JsonlAppender(str(p), stamp={"rank": 2, "run_id": "r1"})
    app.append({"kind": "serve", "event": "start"})
    app.close()
    rec = read_jsonl(str(p))[0]
    assert rec["replica"] == 2 and rec["port"] == 9002
    # and without the fleet env the keys are ABSENT, not null
    monkeypatch.delenv("XFLOW_REPLICA")
    monkeypatch.delenv("XFLOW_REPLICA_PORT")
    p2 = tmp_path / "b.jsonl"
    app2 = JsonlAppender(str(p2), stamp={"rank": 0, "run_id": "r1"})
    app2.append({"kind": "serve", "event": "start"})
    app2.close()
    rec2 = read_jsonl(str(p2))[0]
    assert "replica" not in rec2 and "port" not in rec2


def test_jsonl_appender_is_thread_safe(tmp_path):
    # the router writes ONE appender from request-handler threads,
    # hedge legs, and the health loop at once; interleaved writes
    # would show up as damaged lines and flip metrics_report gates
    from xflow_tpu.jsonl import JsonlAppender, read_jsonl_counted

    p = tmp_path / "router.jsonl"
    app = JsonlAppender(str(p), stamp={"rank": -1, "run_id": "r1"})
    n_threads, n_each = 8, 100

    def writer(t):
        for i in range(n_each):
            app.append({"kind": "serve", "event": "x", "t": t, "i": i})

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    app.close()
    records, skipped = read_jsonl_counted(str(p), warn=False)
    assert skipped == 0
    assert len(records) == n_threads * n_each


def test_serve_window_never_stamps_behind_a_reload_event(tmp_path):
    """The watcher thread appends the reload event while the metrics
    thread holds a pre-swap (generation, step) snapshot for the window
    it is about to flush; the window record lands AFTER the event in
    file order, so stamping the snapshot would make the stream
    non-monotone (metrics_report --check: generation 2 -> 1). The sink
    folds both paths through one high-water mark under one lock."""
    from xflow_tpu.jsonl import read_jsonl
    from xflow_tpu.serve.metrics import ServeMetrics

    path = tmp_path / "serve.jsonl"
    m = ServeMetrics(str(path), every_s=60.0, batch_size=32)
    m.event("start", generation=1, step=20)
    m.observe_batch(2, 3, [0.001], 0.004, [0.005])
    # the reload event wins the race to the file...
    m.event("reload", generation=2, step=50)
    # ...then the flusher shows up with its stale snapshot
    rec = m.maybe_flush(1, 20, force=True)
    assert (rec["generation"], rec["step"]) == (2, 50)
    m.close(2, 50)
    recs = read_jsonl(str(path))
    pairs = [(r["generation"], r["step"]) for r in recs
             if "generation" in r]
    assert pairs == sorted(pairs), pairs
    mr = _metrics_report()
    assert mr.main([str(path), "--check"]) == 0


# --------------------------------------------------- report fleet gates
def _metrics_report():
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    import metrics_report as mr

    return mr


def _serve_rec(run_id="r1", rank=0, gen=0, ts=1.0, **kw):
    base = {"ts": ts, "rank": rank, "run_id": run_id, "gen": gen,
            "kind": "serve", "event": "start"}
    base.update(kw)
    return base


def _write(tmp_path, name, recs):
    p = tmp_path / name
    p.write_text("".join(json.dumps(r) + "\n" for r in recs))
    return str(p)


def test_check_accepts_distinct_replicas(tmp_path):
    mr = _metrics_report()
    ok = _write(tmp_path, "ok.jsonl", [
        _serve_rec(rank=0, replica=0, port=9000),
        _serve_rec(rank=1, replica=1, port=9001),
        _serve_rec(rank=1, replica=1, port=9001, gen=1, ts=2.0),
    ])
    assert mr.main([ok, "--check"]) == 0


def test_check_rejects_replicas_colliding_on_rank(tmp_path):
    mr = _metrics_report()
    bad = _write(tmp_path, "bad.jsonl", [
        _serve_rec(rank=0, replica=0),
        _serve_rec(rank=0, replica=1, ts=2.0, gen=1),
    ])
    assert mr.main([bad, "--check"]) == 2


def test_check_rejects_mixed_replica_stamps_in_one_stream(tmp_path):
    mr = _metrics_report()
    bad = _write(tmp_path, "bad.jsonl", [
        _serve_rec(rank=0, replica=0),
        _serve_rec(rank=0, replica=1, ts=2.0),
    ])
    assert mr.main([bad, "--check"]) == 2


def test_check_rejects_replica_generation_regression(tmp_path):
    mr = _metrics_report()
    bad = _write(tmp_path, "bad.jsonl", [
        _serve_rec(rank=0, replica=0, gen=1, ts=1.0),
        _serve_rec(rank=0, replica=0, gen=0, ts=2.0),
    ])
    assert mr.main([bad, "--check"]) == 2
    # ACROSS replicas different gens are fine (replica 1 restarted,
    # replica 0 did not)
    ok = _write(tmp_path, "ok.jsonl", [
        _serve_rec(rank=0, replica=0, gen=0, ts=1.0),
        _serve_rec(rank=1, replica=1, gen=2, ts=0.5),
    ])
    assert mr.main([ok, "--check"]) == 0


# ------------------------------------------------------ serve_bench knobs
def test_serve_bench_retries_absorb_503(tmp_path):
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    import serve_bench

    rep = FakeReplica(mode="shed")

    # flip the replica healthy shortly into the bench: the 503s before
    # the flip are absorbed by --retries (with backoff), so exit stays 0
    def heal():
        time.sleep(0.3)
        rep.mode = "ok"

    threading.Thread(target=heal, daemon=True).start()
    out = tmp_path / "B.json"
    rc = serve_bench.main([
        "--url", f"http://127.0.0.1:{rep.port}", "--duration", "1.5",
        "--concurrency", "2", "--retries", "40", "--retry-backoff-ms", "50",
        "--deadline-ms", "10000", "--bench-json", str(out),
    ])
    rep.close()
    rec = json.load(open(out))
    assert rc == 0, rec
    assert rec["errors"] == 0
    assert rec["retried"] >= 1 and rec["retry_attempts"] >= rec["retried"]
    assert rec["deadline_exceeded"] == 0


def test_serve_bench_unabsorbed_errors_still_fail(tmp_path):
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    import serve_bench

    rep = FakeReplica(mode="shed")  # 503 forever: retries cannot absorb
    out = tmp_path / "B.json"
    rc = serve_bench.main([
        "--url", f"http://127.0.0.1:{rep.port}", "--duration", "0.8",
        "--concurrency", "1", "--retries", "1", "--bench-json", str(out),
    ])
    rep.close()
    rec = json.load(open(out))
    assert rc == 1
    assert rec["errors"] >= 1


def test_serve_bench_deadline_exceeded_counts_as_error(tmp_path):
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    import serve_bench

    rep = FakeReplica(mode="slow", delay_s=1.0)
    out = tmp_path / "B.json"
    rc = serve_bench.main([
        "--url", f"http://127.0.0.1:{rep.port}", "--duration", "0.9",
        "--concurrency", "1", "--deadline-ms", "200", "--retries", "3",
        "--bench-json", str(out),
    ])
    rep.close()
    rec = json.load(open(out))
    assert rc == 1
    assert rec["deadline_exceeded"] >= 1


def test_serve_bench_hedge_wins_on_slow_server(tmp_path):
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    import serve_bench

    # the server starts slow then heals: the early requests' hedge legs
    # fire (the client-side p99 amputation), later ones answer direct
    rep = FakeReplica(mode="slow", delay_s=0.6)

    def heal():
        time.sleep(0.5)
        rep.mode = "ok"

    threading.Thread(target=heal, daemon=True).start()
    out = tmp_path / "B.json"
    rc = serve_bench.main([
        "--url", f"http://127.0.0.1:{rep.port}", "--duration", "1.2",
        "--concurrency", "1", "--hedge-ms", "120", "--bench-json", str(out),
    ])
    rep.close()
    rec = json.load(open(out))
    assert rc == 0, rec
    assert rec["hedged"] >= 1


# ----------------------------------------------------------- CI chaos drill
def test_smoke_serve_fleet_script(tmp_path):
    """The fleet chaos gate end to end (tools/smoke_serve_fleet.sh):
    train -> 3-replica supervised fleet -> closed-loop bench through
    the router -> SIGKILL one replica mid-load (serve fault injector)
    AND commit a corrupt checkpoint mid-reload -> zero failed client
    requests, the killed replica restarts + rejoins, circuit events in
    the router JSONL, metrics_report --check green, BENCH datapoint."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        ["bash", os.path.join(REPO_ROOT, "tools", "smoke_serve_fleet.sh"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=570, env=env,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "smoke_serve_fleet: OK" in r.stdout
    assert "chaos OK" in r.stdout
    assert "rejoin OK" in r.stdout
    bench = json.load(open(tmp_path / "BENCH_SERVE_FLEET.json"))
    assert bench["metric"] == "serve_qps" and bench["value"] > 0
    assert bench["errors"] == 0
