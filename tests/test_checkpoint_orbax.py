"""Orbax sharded checkpoint path (the at-scale format)."""

import numpy as np
import pytest

from xflow_tpu.config import Config, override
from xflow_tpu.data.synth import generate_shards
from xflow_tpu.models import get_model
from xflow_tpu.optim import get_optimizer
from xflow_tpu.parallel.mesh import make_mesh
from xflow_tpu.parallel.train_step import shard_state
from xflow_tpu.train import init_state
from xflow_tpu.train.checkpoint import latest_orbax_step, restore_orbax, save_orbax
from xflow_tpu.train.trainer import Trainer

pytest.importorskip("orbax.checkpoint")


def test_orbax_roundtrip_sharded(tmp_path):
    cfg = override(Config(), **{"data.log2_slots": 12, "mesh.data": 4, "mesh.table": 2})
    mesh = make_mesh(cfg)
    model, opt = get_model("fm"), get_optimizer("ftrl")
    state = shard_state(init_state(model, opt, cfg), mesh)
    # poke some structure into the tables so the roundtrip is nontrivial
    import jax.numpy as jnp

    state = state._replace(
        tables={**state.tables, "wv": state.tables["wv"] + 0.5},
        step=jnp.asarray(7, jnp.int32),
    )
    save_orbax(str(tmp_path), state)
    assert latest_orbax_step(str(tmp_path)) == 7

    like = shard_state(init_state(model, opt, cfg), mesh)
    restored = restore_orbax(str(tmp_path), like)
    assert int(restored.step) == 7
    np.testing.assert_allclose(np.asarray(restored.tables["wv"]), np.asarray(state.tables["wv"]))
    np.testing.assert_allclose(
        np.asarray(restored.opt_state["wv"]["n"]), np.asarray(state.opt_state["wv"]["n"])
    )
    # restored arrays carry the mesh sharding (shards load in place)
    assert len(restored.tables["wv"].addressable_shards) == 8


def test_orbax_packed_layout_migration(tmp_path):
    """Orbax stores the NATIVE (packed) layout; restoring into a
    packed_tables=off run — or restoring a pre-packed (logical) ckpt into
    a packed run — must migrate by reshape, like the npz path does."""
    import jax.numpy as jnp

    from xflow_tpu.ops.sorted_table import pack_of

    base = {"data.log2_slots": 12}
    cfg_packed = override(Config(), **base)  # auto => packed
    cfg_logical = override(Config(), **{**base, "data.packed_tables": "off"})
    model, opt = get_model("fm"), get_optimizer("ftrl")
    K = 1 + cfg_packed.model.v_dim

    state = init_state(model, opt, cfg_packed)
    assert pack_of(state.tables["wv"], K) > 1
    state = state._replace(
        tables={**state.tables, "wv": state.tables["wv"] + 0.25},
        step=jnp.asarray(3, jnp.int32),
    )
    save_orbax(str(tmp_path), state)

    # packed -> logical
    like = init_state(model, opt, cfg_logical)
    assert pack_of(like.tables["wv"], K) == 1
    restored = restore_orbax(str(tmp_path), like)
    assert restored.tables["wv"].shape == like.tables["wv"].shape
    np.testing.assert_allclose(
        np.asarray(restored.tables["wv"]),
        np.asarray(state.tables["wv"]).reshape(like.tables["wv"].shape),
    )
    assert int(restored.step) == 3

    # logical -> packed (round-trips back to the original packed values)
    save_orbax(str(tmp_path / "logical"), restored)
    back = restore_orbax(str(tmp_path / "logical"), init_state(model, opt, cfg_packed))
    np.testing.assert_allclose(
        np.asarray(back.tables["wv"]), np.asarray(state.tables["wv"])
    )
    np.testing.assert_allclose(
        np.asarray(back.opt_state["wv"]["n"]), np.asarray(state.opt_state["wv"]["n"])
    )


def test_trainer_orbax_resume(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    generate_shards(str(tmp_path / "train"), 1, 600, num_fields=5, ids_per_field=30, seed=0)
    cfg = override(
        Config(),
        **{
            "data.train_path": str(tmp_path / "train"),
            "data.log2_slots": 12,
            "data.batch_size": 100,
            "data.max_nnz": 8,
            "model.num_fields": 5,
            "train.epochs": 2,
            "train.checkpoint_dir": str(tmp_path / "ck"),
            "train.checkpoint_format": "orbax",
        },
    )
    t1 = Trainer(cfg)
    t1.fit()
    assert latest_orbax_step(str(tmp_path / "ck")) == 12
    t2 = Trainer(cfg)
    assert t2.maybe_restore()
    assert int(t2.state.step) == 12
    np.testing.assert_allclose(
        np.asarray(t1.state.tables["w"]), np.asarray(t2.state.tables["w"])
    )
