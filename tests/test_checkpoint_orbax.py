"""Orbax sharded checkpoint path (the at-scale format)."""

import numpy as np
import pytest

from xflow_tpu.config import Config, override
from xflow_tpu.data.synth import generate_shards
from xflow_tpu.models import get_model
from xflow_tpu.optim import get_optimizer
from xflow_tpu.parallel.mesh import make_mesh
from xflow_tpu.parallel.train_step import shard_state
from xflow_tpu.train import init_state
from xflow_tpu.train.checkpoint import latest_orbax_step, restore_orbax, save_orbax
from xflow_tpu.train.trainer import Trainer

pytest.importorskip("orbax.checkpoint")


def test_orbax_roundtrip_sharded(tmp_path):
    cfg = override(Config(), **{"data.log2_slots": 12, "mesh.data": 4, "mesh.table": 2})
    mesh = make_mesh(cfg)
    model, opt = get_model("fm"), get_optimizer("ftrl")
    state = shard_state(init_state(model, opt, cfg), mesh)
    # poke some structure into the tables so the roundtrip is nontrivial
    import jax.numpy as jnp

    state = state._replace(
        tables={**state.tables, "wv": state.tables["wv"] + 0.5},
        step=jnp.asarray(7, jnp.int32),
    )
    save_orbax(str(tmp_path), state)
    assert latest_orbax_step(str(tmp_path)) == 7

    like = shard_state(init_state(model, opt, cfg), mesh)
    restored = restore_orbax(str(tmp_path), like)
    assert int(restored.step) == 7
    np.testing.assert_allclose(np.asarray(restored.tables["wv"]), np.asarray(state.tables["wv"]))
    np.testing.assert_allclose(
        np.asarray(restored.opt_state["wv"]["n"]), np.asarray(state.opt_state["wv"]["n"])
    )
    # restored arrays carry the mesh sharding (shards load in place)
    assert len(restored.tables["wv"].addressable_shards) == 8


def test_orbax_packed_layout_migration(tmp_path):
    """Orbax stores the NATIVE (packed) layout; restoring into a
    packed_tables=off run — or restoring a pre-packed (logical) ckpt into
    a packed run — must migrate by reshape, like the npz path does."""
    import jax.numpy as jnp

    from xflow_tpu.ops.sorted_table import pack_of

    base = {"data.log2_slots": 12}
    cfg_packed = override(Config(), **base)  # auto => packed
    cfg_logical = override(Config(), **{**base, "data.packed_tables": "off"})
    model, opt = get_model("fm"), get_optimizer("ftrl")
    K = 1 + cfg_packed.model.v_dim

    state = init_state(model, opt, cfg_packed)
    assert pack_of(state.tables["wv"], K) > 1
    state = state._replace(
        tables={**state.tables, "wv": state.tables["wv"] + 0.25},
        step=jnp.asarray(3, jnp.int32),
    )
    save_orbax(str(tmp_path), state)

    # packed -> logical
    like = init_state(model, opt, cfg_logical)
    assert pack_of(like.tables["wv"], K) == 1
    restored = restore_orbax(str(tmp_path), like)
    assert restored.tables["wv"].shape == like.tables["wv"].shape
    np.testing.assert_allclose(
        np.asarray(restored.tables["wv"]),
        np.asarray(state.tables["wv"]).reshape(like.tables["wv"].shape),
    )
    assert int(restored.step) == 3

    # logical -> packed (round-trips back to the original packed values)
    save_orbax(str(tmp_path / "logical"), restored)
    back = restore_orbax(str(tmp_path / "logical"), init_state(model, opt, cfg_packed))
    np.testing.assert_allclose(
        np.asarray(back.tables["wv"]), np.asarray(state.tables["wv"])
    )
    np.testing.assert_allclose(
        np.asarray(back.opt_state["wv"]["n"]), np.asarray(state.opt_state["wv"]["n"])
    )


def test_fused_layout_bridge_both_formats(tmp_path):
    """A checkpoint written with one model.fm_fused setting restores
    into the other (round-3 weak #6's last unclosed case): the fused
    wv splits into w/v columns (and FTRL n/z likewise), the two-table
    layout merges — npz AND orbax, with packed storage in play."""
    import jax.numpy as jnp

    from xflow_tpu.train.checkpoint import restore, save

    base = {"data.log2_slots": 12}
    cfg_fused = override(Config(), **base)
    cfg_two = override(Config(), **{**base, "model.fm_fused": False})
    model, opt = get_model("fm"), get_optimizer("ftrl")
    k = cfg_fused.model.v_dim
    S = 1 << 12

    state_f = init_state(model, opt, override(cfg_fused, **{}))
    state_f = state_f._replace(
        tables={"wv": state_f.tables["wv"] + 0.125},
        opt_state={"wv": {kk: vv + 1.0 for kk, vv in state_f.opt_state["wv"].items()}},
        step=jnp.asarray(5, jnp.int32),
    )
    from xflow_tpu.ops.sorted_table import unpack_table

    wv_logical = np.asarray(unpack_table(state_f.tables["wv"], 1 + k))

    # fused -> two-table, npz
    save(str(tmp_path / "npz"), state_f, {"wv": 1 + k})
    like_two = init_state(model, opt, override(Config(), **{**base, "model.fm_fused": False}))
    got = restore(str(tmp_path / "npz"), like_two)
    np.testing.assert_allclose(np.asarray(got.tables["w"]), wv_logical[:, 0])
    np.testing.assert_allclose(
        np.asarray(unpack_table(got.tables["v"], k)), wv_logical[:, 1:]
    )
    n_logical = np.asarray(unpack_table(state_f.opt_state["wv"]["n"], 1 + k))
    np.testing.assert_allclose(np.asarray(got.opt_state["w"]["n"]), n_logical[:, 0])

    # two-table -> fused, npz (round-trip back)
    save(str(tmp_path / "npz2"), got, {"v": k})
    like_fused = init_state(model, opt, cfg_fused)
    back = restore(str(tmp_path / "npz2"), like_fused)
    np.testing.assert_allclose(
        np.asarray(back.tables["wv"]), np.asarray(state_f.tables["wv"])
    )
    np.testing.assert_allclose(
        np.asarray(back.opt_state["wv"]["z"]), np.asarray(state_f.opt_state["wv"]["z"])
    )

    # fused -> two-table, ORBAX (stores the PACKED native layout; the
    # bridge's size-derived reshape is the free unpack)
    save_orbax(str(tmp_path / "ob"), state_f)
    got_ob = restore_orbax(str(tmp_path / "ob"), init_state(model, opt, cfg_two))
    np.testing.assert_allclose(np.asarray(got_ob.tables["w"]), wv_logical[:, 0])
    np.testing.assert_allclose(
        np.asarray(unpack_table(got_ob.tables["v"], k)), wv_logical[:, 1:]
    )
    assert int(got_ob.step) == 5


def test_fused_bridge_does_not_cross_models(tmp_path):
    """The fused<->two-table bridge must NOT fire for other models: a
    fused-FM checkpoint restored into LR (w only) or MVM (v only) is a
    cross-model mistake and stays a loud error, never a silent
    column-slice restore."""
    import jax.numpy as jnp

    from xflow_tpu.train.checkpoint import restore, save

    cfg = override(Config(), **{"data.log2_slots": 12})
    fm_state = init_state(get_model("fm"), get_optimizer("ftrl"), cfg)
    save(str(tmp_path), fm_state, {"wv": 1 + cfg.model.v_dim})
    for other in ("lr", "mvm"):
        like = init_state(get_model(other), get_optimizer("ftrl"), cfg)
        with pytest.raises(RuntimeError, match="different model"):
            restore(str(tmp_path), like)


def test_trainer_orbax_resume(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    generate_shards(str(tmp_path / "train"), 1, 600, num_fields=5, ids_per_field=30, seed=0)
    cfg = override(
        Config(),
        **{
            "data.train_path": str(tmp_path / "train"),
            "data.log2_slots": 12,
            "data.batch_size": 100,
            "data.max_nnz": 8,
            "model.num_fields": 5,
            "train.epochs": 2,
            "train.checkpoint_dir": str(tmp_path / "ck"),
            "train.checkpoint_format": "orbax",
        },
    )
    t1 = Trainer(cfg)
    t1.fit()
    assert latest_orbax_step(str(tmp_path / "ck")) == 12
    t2 = Trainer(cfg)
    assert t2.maybe_restore()
    assert int(t2.state.step) == 12
    np.testing.assert_allclose(
        np.asarray(t1.state.tables["w"]), np.asarray(t2.state.tables["w"])
    )
