"""Multi-process cluster-emulation tests (the `scripts/local.sh` analog).

These run the REAL multi-process path — `xflow launch-local` forks N
`xflow train` processes that rendezvous through
`jax.distributed.initialize` on CPU, form a 2-process world, shard the
tables over the global mesh, and read per-rank input shards
(reference convention `lr_worker.cc:210`: rank k reads `<prefix>-%05d`).

Round-1 verdict: this path was silently broken (children inherited the
ambient accelerator platform, never formed a world, and each trained
shard 0 as its own rank 0) and had zero test coverage. These tests gate:
  - the world actually forms (the launcher now fails loudly otherwise),
  - exactly one rank-0 summary is printed,
  - final tables equal a single-process run on the batch-composed data,
  - ragged / missing shards are tolerated (reference parity: its async
    workers never synchronize, so ragged shards "just work" there).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from xflow_tpu.data.synth import generate_shards

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_cli(args, cwd, extra_env=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    # children get ONE cpu device each (the conftest exports an 8-device
    # XLA_FLAGS for the in-process fake cluster; strip it here)
    env.pop("XFLOW_NUM_CPU_DEVICES", None)
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    if extra_env:
        env.update(extra_env)
    return subprocess.run(
        [sys.executable, "-m", "xflow_tpu", *args],
        capture_output=True,
        text=True,
        cwd=cwd,
        env=env,
        timeout=600,
    )


_MULTIPROC_CPU = None

_PROBE = """
import sys
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(sys.argv[1], 2, int(sys.argv[2]))
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
mesh = Mesh(np.array(jax.devices()), ("d",))
x = jax.device_put(np.zeros(4, np.float32), NamedSharding(mesh, P()))
jax.block_until_ready(x)
print("PROBE_OK")
"""


def _run_probe_once():
    """One 2-process probe run. Returns (ok, combined_output)."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XFLOW_NUM_CPU_DEVICES", None)
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _PROBE, f"127.0.0.1:{port}", str(r)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for r in range(2)
    ]
    ok, outs = True, []
    for pr in procs:
        try:
            out, _ = pr.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            pr.kill()
            out = pr.communicate()[0] or ""
        outs.append(out or "")
        ok = ok and pr.returncode == 0 and "PROBE_OK" in (out or "")
    return ok, "\n".join(outs)


def multiproc_cpu_supported() -> bool:
    """Can this jax build run a 2-process CPU world at all? Some jaxlib
    versions reject multi-process computations on the CPU backend
    ("Multiprocess computations aren't implemented..."), which dooms
    every two-process test here to a slow failure; one cached ~15 s
    probe (a cross-process replicated device_put, the exact op that
    trips first) converts them into immediate skips instead.

    Only the KNOWN incapability message caches False on the first try —
    a transient failure (port stolen between bind and rendezvous, CI
    load) gets one retry, so a capable build cannot be silently skipped
    wholesale by one flake."""
    global _MULTIPROC_CPU
    if _MULTIPROC_CPU is None:
        ok, out = _run_probe_once()
        if not ok and "aren't implemented" not in out:
            ok, out = _run_probe_once()  # transient-looking: retry once
        _MULTIPROC_CPU = ok
    return _MULTIPROC_CPU


def require_multiproc_cpu():
    if not multiproc_cpu_supported():
        pytest.skip("multi-process CPU computations unsupported by this jax build")


def _interleave_shards(paths, block_rows, out_path):
    """Compose the single-process analog of the 2-process global batch
    stream: step i's global batch is [rank0 rows | rank1 rows], so the
    combined file interleaves block_rows-row blocks from each shard."""
    shard_lines = [open(p).read().splitlines() for p in paths]
    n_blocks = max(len(ls) for ls in shard_lines) // block_rows
    out = []
    for b in range(n_blocks):
        for lines in shard_lines:
            out.extend(lines[b * block_rows : (b + 1) * block_rows])
    with open(out_path, "w") as f:
        f.write("\n".join(out) + "\n")


TRAIN_ARGS = [
    "--model", "lr", "--epochs", "2", "--log2-slots", "10",
    "--set", "model.num_fields=4", "--set", "data.max_nnz=8",
    "--set", "train.pred_dump=false",
]


def test_launch_local_two_process_matches_single_process(tmp_path):
    require_multiproc_cpu()
    B, rows = 32, 96  # 3 batches per rank per epoch, no remainder
    generate_shards(str(tmp_path / "train"), 2, rows, num_fields=4, ids_per_field=50)
    generate_shards(
        str(tmp_path / "test"), 2, B, num_fields=4, ids_per_field=50, seed=7, truth_seed=0
    )

    r2 = run_cli(
        ["launch-local", "--num-processes", "2",
         "--run-dir", str(tmp_path / "run2p"), "--",
         "--train", str(tmp_path / "train"), "--test", str(tmp_path / "test"),
         "--batch-size", str(B), "--checkpoint-dir", str(tmp_path / "ckpt2p"),
         # pin EXACT eval: this is the bit-match gate, and the multi-
         # process default (eval_buckets auto) is bucketed — its AUC
         # differs by bucket quantization, not a training divergence
         "--set", "train.eval_buckets=0",
         *TRAIN_ARGS],
        tmp_path,
    )
    assert r2.returncode == 0, r2.stderr
    # --run-dir collected one stamped telemetry stream per rank,
    # joinable on a single shared run_id
    telem = {}
    for rank in (0, 1):
        recs = [
            json.loads(l)
            for l in open(tmp_path / "run2p" / f"metrics_rank{rank}.jsonl")
        ]
        assert recs and all(r["rank"] == rank for r in recs)
        telem[rank] = recs
    assert {r["run_id"] for rs in telem.values() for r in rs} == {
        telem[0][0]["run_id"]
    }
    # exactly one summary line: rank 0's (the round-1 bug printed two)
    summaries = [json.loads(l) for l in r2.stdout.strip().splitlines() if l.startswith("{")]
    assert len(summaries) == 1, r2.stdout
    s2 = summaries[0]
    assert s2["rank"] == 0
    assert s2["steps"] == 2 * (rows // B)  # global steps, not per-rank sums
    assert s2["examples"] == 2 * rows  # rank 0's local rows over 2 epochs

    # single-process run on the batch-composed data
    _interleave_shards(
        [tmp_path / "train-00000", tmp_path / "train-00001"], B, tmp_path / "comb-00000"
    )
    _interleave_shards(
        [tmp_path / "test-00000", tmp_path / "test-00001"], B, tmp_path / "combtest-00000"
    )
    r1 = run_cli(
        ["train", "--train", str(tmp_path / "comb"), "--test", str(tmp_path / "combtest"),
         "--batch-size", str(2 * B), "--checkpoint-dir", str(tmp_path / "ckpt1p"),
         "--no-mesh", *TRAIN_ARGS],
        tmp_path,
    )
    assert r1.returncode == 0, r1.stderr
    s1 = json.loads(r1.stdout.strip().splitlines()[-1])

    d2 = np.load(tmp_path / "ckpt2p" / f"step_{s2['steps']}" / "state.npz")
    d1 = np.load(tmp_path / "ckpt1p" / f"step_{s1['steps']}" / "state.npz")
    assert s1["steps"] == s2["steps"]
    np.testing.assert_allclose(
        d2["tables/w"], d1["tables/w"], rtol=0, atol=1e-6,
        err_msg="2-process sharded tables != single-process tables on composed data",
    )
    np.testing.assert_allclose(d2["opt/w/n"], d1["opt/w/n"], rtol=0, atol=1e-6)
    assert abs(s2["auc"] - s1["auc"]) < 1e-5, (s2["auc"], s1["auc"])


def test_launch_local_ragged_and_missing_shards(tmp_path):
    require_multiproc_cpu()
    # rank 0 has 3 batches, rank 1 only 1: exhausted ranks pad with empty
    # batches until everyone is done (trainer._coordinated_batches)
    B = 32
    generate_shards(str(tmp_path / "train"), 1, 3 * B, num_fields=4, ids_per_field=50)
    generate_shards(str(tmp_path / "short"), 1, B, num_fields=4, ids_per_field=50, seed=3)
    os.rename(tmp_path / "short-00000", tmp_path / "train-00001")
    r = run_cli(
        ["launch-local", "--num-processes", "2", "--",
         "--train", str(tmp_path / "train"), "--batch-size", str(B),
         "--epochs", "1", "--model", "lr", "--log2-slots", "10",
         "--set", "model.num_fields=4", "--set", "data.max_nnz=8"],
        tmp_path,
    )
    assert r.returncode == 0, r.stderr
    s = json.loads(r.stdout.strip().splitlines()[-1])
    assert s["steps"] == 3  # rank 0's 3 batches drive the epoch

    # missing shard entirely: rank 1 finds no train-00001 → empty contribution
    os.remove(tmp_path / "train-00001")
    r = run_cli(
        ["launch-local", "--num-processes", "2", "--",
         "--train", str(tmp_path / "train"), "--batch-size", str(B),
         "--epochs", "1", "--model", "lr", "--log2-slots", "10",
         "--set", "model.num_fields=4", "--set", "data.max_nnz=8"],
        tmp_path,
    )
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout.strip().splitlines()[-1])["steps"] == 3


def test_launch_local_supervised_auto_restart(tmp_path):
    """Elastic-recovery drill (PR 4 acceptance): SIGKILL rank 1 mid-run
    (the env-gated kill injector, testing/faults.py) under
    --max-restarts — the launcher must tear the job down, auto-restart
    it WITHOUT operator action, restore the last committed checkpoint,
    resume the data stream at the stored offset, and finish with the
    exact total example count (the kill lands on a checkpoint boundary,
    so no step is retrained: every row trains exactly once across the
    two generations). metrics_report --check must accept the resulting
    multi-generation stream."""
    require_multiproc_cpu()
    B, rows = 32, 96  # 3 batches/rank/epoch x 2 epochs = 6 global steps
    generate_shards(str(tmp_path / "train"), 2, rows, num_fields=4, ids_per_field=50)
    run_dir = tmp_path / "run"
    r = run_cli(
        ["launch-local", "--num-processes", "2",
         "--max-restarts", "1", "--restart-backoff", "0.2",
         "--run-dir", str(run_dir), "--",
         "--train", str(tmp_path / "train"), "--batch-size", str(B),
         "--checkpoint-dir", str(tmp_path / "ckpt"),
         "--set", "train.checkpoint_every=2",
         "--set", "train.heartbeat_every=1",
         "--set", "train.log_every=1",
         *TRAIN_ARGS],
        tmp_path,
        # kill rank 1 the moment step 4 completes — right after its
        # checkpoint committed (generation-gated: the relaunch survives)
        extra_env={"XFLOW_FAULT_KILL_STEP": "4", "XFLOW_FAULT_KILL_RANK": "1"},
    )
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "hard-killing rank 1 at step 4" in r.stderr
    assert "restarting generation 1" in r.stderr
    assert "resumed from step 4" in r.stderr
    assert "resuming data stream at epoch 1, shard offsets [1, 1]" in r.stderr
    assert "job succeeded after 1 restart(s)" in r.stderr

    # generation 1's rank-0 summary: exactly the un-trained suffix
    summaries = [json.loads(l) for l in r.stdout.strip().splitlines()
                 if l.startswith("{")]
    assert summaries and summaries[-1]["steps"] == 2  # steps 5, 6

    # the final checkpoint is the full run, and its data_state accounts
    # for every row exactly once on BOTH ranks (no replay, no loss)
    from xflow_tpu.train.checkpoint import latest_step, read_data_state

    ck = str(tmp_path / "ckpt")
    assert latest_step(ck) == 6
    ds = read_data_state(ck, 6)
    # GLOBAL accounting (v2 data_state): 2 shards x 96 rows x 2 epochs,
    # every row exactly once; per-rank counts are this GENERATION's
    # local consumption (steps 5-6 = 2 batches x 32 rows each)
    assert ds["completed"] and ds["examples"] == 4 * rows
    assert ds["examples_per_rank"] == [2 * B, 2 * B]
    assert ds["world_size"] == 2 and ds["num_shards"] == 2

    # both generations landed in the run dir under ONE run_id, and the
    # schema gate accepts the multi-generation stream
    recs = [json.loads(l) for l in open(run_dir / "metrics_rank0.jsonl")]
    assert {r_["gen"] for r_ in recs} == {0, 1}
    assert len({r_["run_id"] for r_ in recs}) == 1
    chk = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "metrics_report.py"),
         str(run_dir), "--check"],
        capture_output=True, text=True, timeout=300,
    )
    assert chk.returncode == 0, chk.stderr


@pytest.mark.parametrize("engine", ["fullshard", "replicated"])
def test_launch_local_two_process_sorted_engine(tmp_path, engine):
    """Multi-process sorted engines: 2 processes × 1 device, mesh
    (data=2, table=1), fused FM with sorted_layout=on — final tables
    match a single-process sorted run on the batch-composed data.
    Covers BOTH mesh engines: fullshard (table sharded over the whole
    mesh, occurrence all_to_all crossing the process boundary) and
    replicated (table on the 'table' axis only)."""
    require_multiproc_cpu()
    B, rows = 32, 96
    fm_args = [
        "--model", "fm", "--epochs", "2", "--log2-slots", "13",
        "--set", "model.num_fields=4", "--set", "data.max_nnz=8",
        "--set", "train.pred_dump=false", "--set", "data.sorted_layout=on",
        "--set", f"data.sorted_mesh={engine}",
        # exact eval on both sides: this is an equality gate, and the
        # multi-process default (bucketed) differs by tie quantization
        # on a 64-row test set
        "--set", "train.eval_buckets=0",
    ]
    generate_shards(str(tmp_path / "train"), 2, rows, num_fields=4, ids_per_field=50)
    generate_shards(str(tmp_path / "test"), 2, B, num_fields=4, ids_per_field=50,
                    seed=7, truth_seed=0)
    r2 = run_cli(
        ["launch-local", "--num-processes", "2", "--",
         "--train", str(tmp_path / "train"), "--test", str(tmp_path / "test"),
         "--batch-size", str(B),
         "--checkpoint-dir", str(tmp_path / "ckpt2p"), *fm_args],
        tmp_path,
    )
    assert r2.returncode == 0, r2.stderr
    s2 = json.loads(r2.stdout.strip().splitlines()[-1])
    assert s2["steps"] == 2 * (rows // B)

    _interleave_shards(
        [tmp_path / "train-00000", tmp_path / "train-00001"], B, tmp_path / "comb-00000"
    )
    _interleave_shards(
        [tmp_path / "test-00000", tmp_path / "test-00001"], B, tmp_path / "combtest-00000"
    )
    r1 = run_cli(
        ["train", "--train", str(tmp_path / "comb"), "--test", str(tmp_path / "combtest"),
         "--batch-size", str(2 * B),
         "--checkpoint-dir", str(tmp_path / "ckpt1p"), "--no-mesh", *fm_args],
        tmp_path,
    )
    assert r1.returncode == 0, r1.stderr
    s1 = json.loads(r1.stdout.strip().splitlines()[-1])
    assert s1["steps"] == s2["steps"]
    # the fullshard engine's multi-process eval consumes the host plan
    # (sorted-plan eval, round-3 item 7) and must match the
    # single-process eval on the composed test set
    assert abs(s2["auc"] - s1["auc"]) < 1e-5, (s2["auc"], s1["auc"])

    d2 = np.load(tmp_path / "ckpt2p" / f"step_{s2['steps']}" / "state.npz")
    d1 = np.load(tmp_path / "ckpt1p" / f"step_{s1['steps']}" / "state.npz")
    np.testing.assert_allclose(
        d2["tables/wv"], d1["tables/wv"], rtol=1e-5, atol=1e-6,
        err_msg="2-process sorted-sharded tables != single-process sorted tables",
    )
    np.testing.assert_allclose(d2["opt/wv/n"], d1["opt/wv/n"], rtol=1e-5, atol=1e-6)


def test_launch_local_two_process_fullshard_ffm(tmp_path):
    """Multi-process FFM on the fullshard engine (the widest-row model:
    the segment-mode a2a ships [1+nf*k]-channel buffers across the
    process boundary): final tables match a single-process run on the
    batch-composed data."""
    require_multiproc_cpu()
    B, rows = 32, 96
    ffm_args = [
        "--model", "ffm", "--epochs", "2", "--log2-slots", "13",
        "--set", "model.num_fields=4", "--set", "model.v_dim=3",
        "--set", "data.max_nnz=8",
        "--set", "train.pred_dump=false", "--set", "data.sorted_layout=on",
        "--set", "data.sorted_mesh=fullshard",
    ]
    generate_shards(str(tmp_path / "train"), 2, rows, num_fields=4, ids_per_field=50)
    r2 = run_cli(
        ["launch-local", "--num-processes", "2", "--",
         "--train", str(tmp_path / "train"), "--batch-size", str(B),
         "--checkpoint-dir", str(tmp_path / "ckpt2p"), *ffm_args],
        tmp_path,
    )
    assert r2.returncode == 0, (r2.stdout, r2.stderr)
    s2 = json.loads(r2.stdout.strip().splitlines()[-1])
    assert s2["steps"] == 2 * (rows // B)

    _interleave_shards(
        [tmp_path / "train-00000", tmp_path / "train-00001"], B, tmp_path / "comb-00000"
    )
    r1 = run_cli(
        ["train", "--train", str(tmp_path / "comb"), "--batch-size", str(2 * B),
         "--checkpoint-dir", str(tmp_path / "ckpt1p"), "--no-mesh", *ffm_args],
        tmp_path,
    )
    assert r1.returncode == 0, r1.stderr
    s1 = json.loads(r1.stdout.strip().splitlines()[-1])
    assert s1["steps"] == s2["steps"]
    d2 = np.load(tmp_path / "ckpt2p" / f"step_{s2['steps']}" / "state.npz")
    d1 = np.load(tmp_path / "ckpt1p" / f"step_{s1['steps']}" / "state.npz")
    np.testing.assert_allclose(
        d2["tables/wv"], d1["tables/wv"], rtol=1e-4, atol=1e-6,
        err_msg="2-process fullshard ffm != single-process on composed data",
    )


def test_launch_local_two_process_mvm_auto_dup_coordination(tmp_path):
    """ADVICE r3: multi-process MVM `mvm_exclusive=auto` must not raise
    (or desync) on duplicate fields. Only rank 0's FIRST batch has a
    row with a repeated field; the per-batch flag allgather must route
    that batch to the segment mode on BOTH ranks (rank 1's rows are
    clean) and the next batch back to the product mode — matching the
    single-process auto run on the batch-composed data, which sees the
    same duplicate pattern per global batch."""
    require_multiproc_cpu()
    B, rows = 32, 64
    rng = np.random.default_rng(9)

    def clean_row(label):
        feats = " ".join(f"{fg}:{rng.integers(0, 50)}:1.0" for fg in range(4))
        return f"{label}\t{feats}"

    with open(tmp_path / "train-00000", "w") as f:
        for i in range(rows):
            if i < B:  # first batch: field 2 repeated -> duplicate
                feats = " ".join(
                    [f"2:{rng.integers(0, 50)}:1.0", f"2:{rng.integers(0, 50)}:1.0"]
                    + [f"{fg}:{rng.integers(0, 50)}:1.0" for fg in (0, 1, 3)]
                )
                f.write(f"{i % 2}\t{feats}\n")
            else:
                f.write(clean_row(i % 2) + "\n")
    with open(tmp_path / "train-00001", "w") as f:
        for i in range(rows):
            f.write(clean_row((i + 1) % 2) + "\n")

    mvm_args = [
        "--model", "mvm", "--epochs", "1", "--log2-slots", "13",
        "--set", "model.num_fields=4", "--set", "data.max_nnz=8",
        "--set", "train.pred_dump=false", "--set", "data.sorted_layout=on",
        "--set", "data.sorted_mesh=fullshard",
        "--set", "model.mvm_exclusive=auto",
    ]
    r2 = run_cli(
        ["launch-local", "--num-processes", "2", "--",
         "--train", str(tmp_path / "train"), "--batch-size", str(B),
         "--checkpoint-dir", str(tmp_path / "ckpt2p"), *mvm_args],
        tmp_path,
    )
    assert r2.returncode == 0, (r2.stdout, r2.stderr)
    s2 = json.loads(r2.stdout.strip().splitlines()[-1])
    assert s2["steps"] == rows // B

    _interleave_shards(
        [tmp_path / "train-00000", tmp_path / "train-00001"], B, tmp_path / "comb-00000"
    )
    r1 = run_cli(
        ["train", "--train", str(tmp_path / "comb"), "--batch-size", str(2 * B),
         "--checkpoint-dir", str(tmp_path / "ckpt1p"), "--no-mesh", *mvm_args],
        tmp_path,
    )
    assert r1.returncode == 0, r1.stderr
    s1 = json.loads(r1.stdout.strip().splitlines()[-1])
    assert s1["steps"] == s2["steps"]
    d2 = np.load(tmp_path / "ckpt2p" / f"step_{s2['steps']}" / "state.npz")
    d1 = np.load(tmp_path / "ckpt1p" / f"step_{s1['steps']}" / "state.npz")
    np.testing.assert_allclose(
        d2["tables/v"], d1["tables/v"], rtol=1e-4, atol=1e-6,
        err_msg="2-process mvm auto dup-coordination != single-process",
    )


def test_launch_local_two_process_fullshard_hot_key_fallback(tmp_path):
    """Round-3 weak #1 gate: a hot feature skewed beyond the fullshard
    buffer capacity must NOT kill a multi-process run. Rank 0's shard
    carries a 100%-frequency feature (6 of 8 occurrences per row — its
    owner block gets ~75% of the shard's occurrences, far over slack
    1.25); rank 1's shard is uniform, so ONLY rank 0 overflows — the
    asymmetric case where rank 1 must drop its own (successful) plan via
    the per-batch flag allgather and join rank 0 on the GSPMD row-major
    step. Gate: trains through, warns, and bit-matches the
    single-process run on the batch-composed data. Reference behavior
    matched: ps-lite serves hot keys slowly but never dies
    (`/root/reference/src/optimizer/ftrl.h:54-79`)."""
    require_multiproc_cpu()
    B, rows = 1024, 2048
    rng = np.random.default_rng(5)
    hot = " ".join(["0:0:1.0"] * 6)
    with open(tmp_path / "train-00000", "w") as f:
        for i in range(rows):
            feats = " ".join(
                f"{fg}:{rng.integers(0, 50)}:1.0" for fg in (1, 2)
            )
            f.write(f"{i % 2}\t{hot} {feats}\n")
    with open(tmp_path / "train-00001", "w") as f:
        for i in range(rows):
            feats = " ".join(
                f"{fg}:{rng.integers(0, 50)}:1.0" for fg in range(1, 4) for _ in range(2)
            )
            f.write(f"{(i + 1) % 2}\t{feats}\n")
    fm_args = [
        "--model", "fm", "--epochs", "1", "--log2-slots", "13",
        "--set", "model.num_fields=4", "--set", "data.max_nnz=8",
        "--set", "train.pred_dump=false", "--set", "data.sorted_layout=on",
        "--set", "data.sorted_mesh=fullshard",
        "--set", "data.fullshard_slack=1.25",
    ]
    r2 = run_cli(
        ["launch-local", "--num-processes", "2", "--",
         "--train", str(tmp_path / "train"), "--batch-size", str(B),
         "--checkpoint-dir", str(tmp_path / "ckpt2p"), *fm_args],
        tmp_path,
    )
    assert r2.returncode == 0, r2.stderr
    assert "falling back to the GSPMD row-major step" in r2.stderr
    s2 = json.loads(r2.stdout.strip().splitlines()[-1])
    assert s2["steps"] == rows // B

    _interleave_shards(
        [tmp_path / "train-00000", tmp_path / "train-00001"], B, tmp_path / "comb-00000"
    )
    r1 = run_cli(
        ["train", "--train", str(tmp_path / "comb"), "--batch-size", str(2 * B),
         "--checkpoint-dir", str(tmp_path / "ckpt1p"), "--no-mesh", *fm_args],
        tmp_path,
    )
    assert r1.returncode == 0, r1.stderr
    s1 = json.loads(r1.stdout.strip().splitlines()[-1])
    assert s1["steps"] == s2["steps"]
    d2 = np.load(tmp_path / "ckpt2p" / f"step_{s2['steps']}" / "state.npz")
    d1 = np.load(tmp_path / "ckpt1p" / f"step_{s1['steps']}" / "state.npz")
    np.testing.assert_allclose(
        d2["tables/wv"], d1["tables/wv"], rtol=1e-4, atol=1e-6,
        err_msg="2-process hot-key fallback != single-process on composed data",
    )
    np.testing.assert_allclose(d2["opt/wv/n"], d1["opt/wv/n"], rtol=1e-4, atol=1e-6)


def test_launch_local_two_process_fullshard_mvm_product(tmp_path):
    """Multi-process MVM on the fullshard engine's exclusive-fields
    PRODUCT path (no fs_fields; synth data is one-feature-per-field, so
    multi-process auto routing takes the product mode on every rank):
    final tables match a single-process run on the batch-composed data."""
    require_multiproc_cpu()
    B, rows = 32, 96
    mvm_args = [
        "--model", "mvm", "--epochs", "2", "--log2-slots", "13",
        "--set", "model.num_fields=4", "--set", "data.max_nnz=8",
        "--set", "train.pred_dump=false", "--set", "data.sorted_layout=on",
        "--set", "data.sorted_mesh=fullshard",
    ]
    generate_shards(str(tmp_path / "train"), 2, rows, num_fields=4, ids_per_field=50)
    r2 = run_cli(
        ["launch-local", "--num-processes", "2", "--",
         "--train", str(tmp_path / "train"), "--batch-size", str(B),
         "--checkpoint-dir", str(tmp_path / "ckpt2p"), *mvm_args],
        tmp_path,
    )
    assert r2.returncode == 0, r2.stderr
    s2 = json.loads(r2.stdout.strip().splitlines()[-1])

    _interleave_shards(
        [tmp_path / "train-00000", tmp_path / "train-00001"], B, tmp_path / "comb-00000"
    )
    r1 = run_cli(
        ["train", "--train", str(tmp_path / "comb"), "--batch-size", str(2 * B),
         "--checkpoint-dir", str(tmp_path / "ckpt1p"), "--no-mesh", *mvm_args],
        tmp_path,
    )
    assert r1.returncode == 0, r1.stderr
    s1 = json.loads(r1.stdout.strip().splitlines()[-1])
    assert s1["steps"] == s2["steps"]
    d2 = np.load(tmp_path / "ckpt2p" / f"step_{s2['steps']}" / "state.npz")
    d1 = np.load(tmp_path / "ckpt1p" / f"step_{s1['steps']}" / "state.npz")
    np.testing.assert_allclose(
        d2["tables/v"], d1["tables/v"], rtol=1e-4, atol=1e-6,
        err_msg="2-process fullshard mvm-product != single-process",
    )
    np.testing.assert_allclose(d2["opt/v/n"], d1["opt/v/n"], rtol=1e-4, atol=1e-6)
